"""Sharded, fully-jitted hybrid-parallel train step.

Reference analogs, collapsed into one component:
- `fleet.distributed_model` wrapper selection (fleet/model.py:32)
- EagerReducer fused grad allreduce (collective/reducer.cc:1067)
- DygraphShardingOptimizer / GroupShardedStage2/3 (ZeRO 1/2/3)
- HybridParallelOptimizer grad-clip-across-groups
  (hybrid_parallel_optimizer.py:254)
- static-graph Engine._parallel (auto_parallel/static/engine.py:764)
- multi-step `Executor.run` amortization (the pipelined hot path below)

TPU-native design: ONE jitted program per training step. Parameters,
optimizer slots and the batch carry NamedShardings over the hybrid mesh
(dp/pp/sharding/sep/mp); XLA/GSPMD then *derives* every collective the
reference implements imperatively: grad all-reduce over dp (reducer),
all-gather of ZeRO-sharded params before use + reduce-scatter of grads
(stages 1-3), mp all-reduces inside TP blocks. Buffers are donated so
parameter memory updates in place in HBM.

Pipelined hot path (PR 3): the per-step host work is driven to ~zero —
batch placement uses cached per-ndim NamedShardings, the learning rate
and step counter live on device (the step counter and RNG key are donated
carry state incremented/split in-graph), and live Parameter objects
resolve lazily against engine state (core.lazy.EngineRef) instead of
being reassigned every step. `train_batches` runs N optimizer steps per
dispatch via `lax.scan` (with a fused variant for a static repeated
batch), so nothing host-side executes between micro-steps.
"""
from __future__ import annotations

import itertools
import time

import numpy as np
import jax
import jax.numpy as jnp

from ..analysis import commcheck as _cc
from ..analysis import graphcheck as _gc
from ..analysis import runtime_san as _san
from ..core import lazy as _lazy
from ..core.tensor import Tensor
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from ..optimizer.lr import LRScheduler
from ..ops import random as rng_mod
from .functional import functionalize
from .sharding_spec import (
    DEFAULT_TP_RULES, spec_for_param, opt_state_spec,
)
from . import topology as topo_mod
# placement is resolved by the ONE sharding authority (paddle_tpu.sharding);
# batch-spec helpers are re-exported under their historic names
from ..sharding import (
    batch_spec_for_ndim, default_batch_spec,  # noqa: F401 (re-export)
    named_sharding as _named_sharding,
    replicated as _replicated,
    stacked_batch_spec as _stacked_batch_spec,
)


def _is_float(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


_prof_mod = None

#: registry collector keys need a distinct name per engine instance
_ENGINE_OBS_SEQ = itertools.count()


def _span(name, histogram=None):
    """`profiler.profiled_span` indirection: a RecordEvent span when a
    host profiler is actively recording, else a no-op — keeps the native
    tracer (and its first-use build) entirely off the un-profiled hot
    path. With `histogram=` the span ALSO feeds that obs latency
    histogram on every pass, recording or not."""
    global _prof_mod
    if _prof_mod is None:
        from .. import profiler as _p
        _prof_mod = _p
    return _prof_mod.profiled_span(name, histogram=histogram)


def _clip_grads(grads, clip):
    """Functional grad clip (reference: ClipGradByGlobalNorm nn/clip.py,
    applied across all hybrid groups by HybridParallelOptimizer — here grads
    are already global values, so one global norm is THE cross-group norm)."""
    if clip is None:
        return grads
    if isinstance(clip, ClipGradByGlobalNorm):
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in grads.values()))
        scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(norm, 1e-12))
        return dict((k, (g.astype(jnp.float32) * scale).astype(g.dtype))
                           for k, g in grads.items())
    if isinstance(clip, ClipGradByNorm):
        out = {}
        for k, g in grads.items():
            n = jnp.linalg.norm(g.astype(jnp.float32).reshape(-1))
            s = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(n, 1e-12))
            out[k] = (g.astype(jnp.float32) * s).astype(g.dtype)
        return out
    if isinstance(clip, ClipGradByValue):
        return dict(
            (k, jnp.clip(g, clip.min, clip.max)) for k, g in grads.items())
    return grads


class ShardedTrainStep:
    """Compile `loss_fn(model, *batch)` + optimizer update into one sharded
    XLA program over the hybrid mesh."""

    def __init__(self, model, optimizer, loss_fn=None, hcg=None,
                 sharding_stage=0, rules=None, compute_dtype=None,
                 batch_spec=None, donate=True, context_parallel="ring"):
        self.model = model
        self.optimizer = optimizer
        self.hcg = hcg or topo_mod.get_hybrid_communicate_group()
        if self.hcg is None:
            self.hcg = topo_mod.HybridCommunicateGroup(
                mesh=topo_mod.build_mesh(dp=-1))
            topo_mod.set_hybrid_communicate_group(self.hcg)
        self.mesh = self.hcg.mesh
        self.sharding_stage = sharding_stage
        self.rules = DEFAULT_TP_RULES if rules is None else rules
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.donate = donate
        # context-parallel attention over the sep axis ("ring" | "ulysses" |
        # None); model-level sdpa calls reroute inside the traced step.
        self.context_parallel = context_parallel

        if loss_fn is None:
            if not hasattr(model, "loss"):
                raise ValueError("pass loss_fn or give the model a .loss")
            loss_fn = lambda m, *batch: m.loss(*batch)  # noqa: E731
        self._apply, self._params, self._buffers = functionalize(
            model, method=lambda *b: loss_fn(model, *b))

        # ---- shardings (built ONCE; the hot path only does dict reads) --
        mesh = self.mesh
        self.param_specs = dict(
            (n, spec_for_param(n, p, self.rules,
                               sharding_stage=sharding_stage, mesh=mesh))
            for n, p in self._params.items())
        self.state_specs = dict(
            (n, opt_state_spec(self.param_specs[n], p.shape, mesh,
                               sharding_stage=sharding_stage))
            for n, p in self._params.items())
        if batch_spec is None:
            batch_spec = default_batch_spec(mesh)
        self.batch_spec = batch_spec
        self._param_sh = {n: _named_sharding(mesh, s)
                          for n, s in self.param_specs.items()}
        self._state_sh = {n: _named_sharding(mesh, s)
                          for n, s in self.state_specs.items()}
        self._scalar_sh = _replicated(mesh)
        self._batch_sh_cache = {}   # ndim -> NamedSharding

        # ---- place values ---------------------------------------------
        self.param_vals = {}
        for n, p in self._params.items():
            p._value = jax.device_put(p._value, self._param_sh[n])
            self.param_vals[n] = p._value
        self.buffer_vals = {}
        self._buf_sh = {}
        for n, b in self._buffers.items():
            sh = _replicated(mesh, b.ndim)
            self._buf_sh[n] = sh
            b._value = jax.device_put(b._value, sh)
            self.buffer_vals[n] = b._value

        # optimizer slots, sharded per state_specs (None optimizer = eval-only
        # engine; train_batch will refuse)
        self.opt_state = {}
        if self.optimizer is not None:
            for n, p in self._params.items():
                names = self.optimizer._state_names
                sh = self._state_sh[n]
                self.opt_state[n] = {
                    s: jax.device_put(jnp.zeros(p.shape, p.dtype), sh)
                    for s in names}

        # ---- lazy parameter write-back ---------------------------------
        # Live Parameters resolve against engine state on read (EngineRef)
        # instead of being reassigned every step. External writes replace
        # the ref; _adopt_external_writes() picks them up (identity check,
        # no per-step property work).
        self._param_refs = []
        for n, p in self._params.items():
            v = self.param_vals[n]
            ref = _lazy.EngineRef(
                (lambda eng=self, k=n: eng.param_vals[k]), v.shape, v.dtype)
            p._value = ref
            self._param_refs.append((n, p, ref))

        self._step_fn = None
        self._eval_fns = {}
        self._multi_fns = {}
        self._step_count = 0
        self.last_grad_norm = None
        self.last_grad_norms = None
        # device-resident per-step scalars: lr re-put only when the host
        # value changes; step counter and RNG key are donated carry state
        self._lr_host = None
        self._lr_dev = None
        self._step_dev = None
        self._key_dev = None
        self._key_epoch = None
        # most-recent (n, lr) -> device (n,) array for constant lr; a
        # single entry so host-driven lr decay can't grow it unboundedly
        self._lrs_key = None
        self._lrs_dev = None
        # dispatch-count hook: host dispatches of compiled step programs and
        # explicit host->device transfers, for perf smoke tests that must
        # not depend on wall-clock
        self.stats = {"dispatches": 0, "device_puts": 0, "steps": 0}
        # in-flight dispatch marker (site, monotonic start), set around
        # every compiled-step dispatch: the training watchdog
        # (train_guard.TrainWatchdog) flags a dispatch that exceeds its
        # timeout as a wedged collective/device hang
        self._inflight = None
        # telemetry (paddle_tpu.obs): the SAME stats dict registered as a
        # weakly-held collector (the registry prunes it when the engine is
        # garbage-collected), plus a dispatch-latency histogram fed by the
        # engine::dispatch spans below whether or not a profiler records
        from ..obs.metrics import registry as _obs_registry

        self._obs_key = f"train.engine{next(_ENGINE_OBS_SEQ)}"
        # the engine's mesh never changes, so its tpu-san sharding
        # signature is computed once (the per-call probes below ride it
        # on the dispatch hot path)
        self._san_mesh_sig = _san.sharding_signature(mesh)
        self._h_dispatch = _obs_registry().histogram(
            "engine.dispatch_seconds",
            help="host-side latency of one compiled train/eval step "
                 "dispatch (enqueue, not device completion)")
        _obs_registry().register_collector(self._obs_key,
                                           self._obs_collect)
        # sharding telemetry: mesh shape + per-param shard fractions under
        # `sharding.train.engineN` (docs/sharding.md); a bound method, so
        # the registry holds it weakly and prunes with the engine
        self._sharding_obs_key = f"sharding.{self._obs_key}"
        _obs_registry().register_collector(self._sharding_obs_key,
                                           self._sharding_obs_collect)

    # ------------------------------------------------------------------
    def _obs_collect(self):
        """Registry collector: the engine's dispatch counters, weakly
        held (see __init__) so a dropped engine un-registers itself."""
        return dict(self.stats)

    def _sharding_obs_collect(self):
        """`sharding.<name>` collector: mesh shape + per-param shard
        fractions (weakly held, like _obs_collect)."""
        from ..sharding import mesh_stats

        return mesh_stats(self.mesh, self.param_specs)

    # ------------------------------------------------------------------
    def _cp_guard(self):
        """Context manager enabling context-parallel attention during trace
        (no-op when the mesh has no sequence axis > 1 or
        context_parallel=None). The sequence axis is resolved through the
        AxisRules "seq" entries, so "sep" (hybrid topology) and "cp"
        (MeshConfig) meshes both route without engine-side special
        cases."""
        import contextlib

        from ..sharding import resolve_axis
        if not self.context_parallel:
            return contextlib.nullcontext()
        seq_axis = resolve_axis("seq", mesh=self.mesh)
        if not isinstance(seq_axis, str):
            return contextlib.nullcontext()
        from .context_parallel import context_parallel_guard
        return context_parallel_guard(self.mesh, mode=self.context_parallel,
                                      seq_axis=seq_axis)

    # ---- cached placement helpers (shared by train/eval/prefetch) -----
    def _batch_sharding(self, ndim):
        sh = self._batch_sh_cache.get(ndim)
        if sh is None:
            sh = _named_sharding(self.mesh, self._batch_spec_for(ndim))
            self._batch_sh_cache[ndim] = sh
        return sh

    def _place_batch(self, batch):
        """Tensors/arrays -> sharded device arrays via the per-ndim cached
        NamedShardings. Values already carrying the target sharding (e.g.
        from prefetch_to_device) are passed through untouched."""
        placed = []
        nputs = 0
        san = _san.enabled()
        for b in batch:
            v = b._value if isinstance(b, Tensor) else jnp.asarray(b)
            if san:
                # donation guard: a batch built from a buffer the engine
                # donated last step fails HERE with the donation site,
                # not inside XLA with "Array has been deleted"
                _san.check_use(v, "engine.place_batch")
            sh = self._batch_sharding(v.ndim)
            if getattr(v, "sharding", None) != sh:
                v = jax.device_put(v, sh)
                nputs += 1
            placed.append(v)
        self.stats["device_puts"] += nputs
        return tuple(placed)

    def _lr_scalar(self):
        lr = self.optimizer.get_lr()
        if self._lr_dev is None or lr != self._lr_host:
            self._lr_host = lr
            self._lr_dev = jax.device_put(jnp.asarray(lr, jnp.float32),
                                          self._scalar_sh)
            self.stats["device_puts"] += 1
        return self._lr_dev

    def _step_scalar(self):
        if self._step_dev is None:
            self._step_dev = jax.device_put(
                jnp.asarray(self._step_count + 1, jnp.int32), self._scalar_sh)
            self.stats["device_puts"] += 1
        return self._step_dev

    def _key_scalar(self):
        # the RNG key is donated carry state split in-graph; a mid-run
        # paddle.seed()/set_state() bumps the seed epoch and must refresh
        # the cached key or the reseed would be silently ignored
        epoch = rng_mod.seed_epoch()
        if self._key_dev is None or self._key_epoch != epoch:
            self._key_epoch = epoch
            self._key_dev = jax.device_put(rng_mod.next_key(),
                                           self._scalar_sh)
            self.stats["device_puts"] += 1
        return self._key_dev

    def _adopt_external_writes(self):
        """A write to an engine-managed Parameter (load_state_dict, manual
        surgery) replaces its EngineRef; fold the new value into engine
        state and re-install the ref. Identity checks only on the common
        path — no property-setter work per step."""
        for n, p, ref in self._param_refs:
            if p._v_ is not ref:
                if _san.enabled():
                    _san.check_use(p._value,
                                   f"engine.adopt_external_write[{n}]")
                self.param_vals[n] = jax.device_put(p._value,
                                                    self._param_sh[n])
                self.stats["device_puts"] += 1
                p._v_ = ref

    # ---- step program --------------------------------------------------
    def _make_step(self):
        """The pure single-step function shared by the one-step jit and the
        lax.scan multi-step variants: carries (params, opt_state, buffers,
        key, step_no) with the RNG split and step increment in-graph."""
        apply_fn = self._apply
        opt = self.optimizer
        clip = getattr(opt, "_grad_clip", None)
        compute_dtype = self.compute_dtype
        cp_guard = self._cp_guard

        def loss_of(params, buffers, batch, key):
            if compute_dtype is not None:
                params = {n: (v.astype(compute_dtype) if _is_float(v) else v)
                          for n, v in params.items()}
                # float batch inputs (images, features) join the compute
                # dtype too — conv/matmul require matching operand dtypes
                batch = tuple(b.astype(compute_dtype) if _is_float(b) else b
                              for b in batch)
            rng_mod.push_trace_key(key)
            try:
                with cp_guard():
                    loss, new_buf = apply_fn(params, buffers, *[
                        Tensor(b) for b in batch])
            finally:
                rng_mod.pop_trace_key()
            return loss, new_buf

        def step(params, opt_state, buffers, batch, lr, key, step_no):
            key, sub = jax.random.split(key)
            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, buffers, batch, sub)
            grads = dict(
                (n, g.astype(params[n].dtype)) for n, g in grads.items())
            # pre-clip global grad norm, exposed for parity/diagnostics
            # (sharding bugs show up in the grad-norm trajectory steps before
            # they move the loss); XLA CSEs this with the clip's own norm
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                 for g in grads.values()))
            grads = _clip_grads(grads, clip)
            new_params = {}
            new_state = {}
            for n, p in params.items():
                np_, ns = opt._update_one(p, grads[n], opt_state[n], lr,
                                          step_no)
                new_params[n] = np_
                new_state[n] = ns
            return (loss, gnorm, new_params, new_state, new_buf, key,
                    step_no + 1)

        return step

    def _opt_state_sh(self):
        return {n: {s: self._state_sh[n] for s in self.opt_state[n]}
                for n in self.opt_state}

    def _build_step(self, batch_avals):
        step = self._make_step()
        param_sh = self._param_sh
        state_sh = self._opt_state_sh()
        buf_sh = self._buf_sh
        batch_sh = tuple(self._batch_sharding(a.ndim) for a in batch_avals)
        scalar_sh = self._scalar_sh

        return jax.jit(
            step,
            in_shardings=(param_sh, state_sh, buf_sh, batch_sh, scalar_sh,
                          scalar_sh, scalar_sh),
            out_shardings=(scalar_sh, scalar_sh, param_sh, state_sh, buf_sh,
                           scalar_sh, scalar_sh),
            # donate the whole carried state: params, slots, buffers, RNG
            # key and step counter update in place in HBM (lr is reused
            # across steps and stays un-donated)
            donate_argnums=(0, 1, 2, 5, 6) if self.donate else (),
        )

    def _build_multi(self, batch_avals, static):
        # scan length comes from the (n,) lrs xs; the _multi_fns cache key
        # carries n so each micro-step count compiles its own program
        step = self._make_step()
        param_sh = self._param_sh
        state_sh = self._opt_state_sh()
        buf_sh = self._buf_sh
        scalar_sh = self._scalar_sh

        def body(carry, x):
            params, opt_state, buffers, key, step_no = carry
            batch, lr = x
            loss, gnorm, params, opt_state, buffers, key, step_no = step(
                params, opt_state, buffers, batch, lr, key, step_no)
            return (params, opt_state, buffers, key, step_no), (loss, gnorm)

        if static:
            # fused variant for a static batch: the batch rides along as a
            # scan-invariant operand — no stacking, no duplicated HBM
            def multi(params, opt_state, buffers, batch, lrs, key, step0):
                carry = (params, opt_state, buffers, key, step0)
                carry, (losses, gnorms) = jax.lax.scan(
                    lambda c, lr: body(c, (batch, lr)), carry, lrs)
                params, opt_state, buffers, key, step_no = carry
                return (losses, gnorms, params, opt_state, buffers, key,
                        step_no)

            batch_sh = tuple(self._batch_sharding(a.ndim)
                             for a in batch_avals)
        else:
            # per-step batches stacked on a leading scan axis
            def multi(params, opt_state, buffers, batches, lrs, key, step0):
                carry = (params, opt_state, buffers, key, step0)
                carry, (losses, gnorms) = jax.lax.scan(
                    lambda c, x: body(c, x), carry, (batches, lrs))
                params, opt_state, buffers, key, step_no = carry
                return (losses, gnorms, params, opt_state, buffers, key,
                        step_no)

            batch_sh = tuple(
                _named_sharding(self.mesh,
                                _stacked_batch_spec(self.batch_spec, a.ndim))
                for a in batch_avals)

        return jax.jit(
            multi,
            in_shardings=(param_sh, state_sh, buf_sh, batch_sh, scalar_sh,
                          scalar_sh, scalar_sh),
            out_shardings=(scalar_sh, scalar_sh, param_sh, state_sh, buf_sh,
                           scalar_sh, scalar_sh),
            donate_argnums=(0, 1, 2, 5, 6) if self.donate else (),
        )

    def _batch_spec_for(self, ndim):
        return batch_spec_for_ndim(self.batch_spec, ndim)

    def declared_state(self):
        """(avals, specs) of the engine's full declared state — params
        plus optimizer slots (keyed ``opt/<param>/<slot>``, sharded like
        their param). The one enumeration behind both the graphcheck
        ``<site>::params`` per-chip watermark and the BENCH_POD state
        gate (`graphcheck.params_bytes_per_chip`)."""
        avals = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for n, v in self.param_vals.items()}
        specs = dict(self.param_specs)
        for n, slots in self.opt_state.items():
            for s, v in slots.items():
                avals[f"opt/{n}/{s}"] = jax.ShapeDtypeStruct(v.shape,
                                                             v.dtype)
                specs[f"opt/{n}/{s}"] = self.state_specs[n]
        return avals, specs

    def _audit_graph(self, site, fn, args):
        """Graph auditor (PADDLE_TPU_GRAPHCHECK=1): statically audit the
        freshly built step program — collectives vs the declared specs,
        conv-region layout changes, host transfers, donation actually
        aliased, live-memory watermark. Costs one extra AOT
        lower+compile per cold entrypoint; free when off.
        `expect_sharded_params` stays False: fsdp-style training gathers
        params in-graph by design (serving entrypoints pass True).
        Optimizer slots join the declared set (`declared_state`) so the
        `<site>::params` per-chip watermark covers param + opt-state
        residency — the number the fsdp memory ratchet gates
        (docs/sharding.md)."""
        param_avals, param_specs = self.declared_state()
        _gc.audit_executable(
            site, jit_obj=fn, args=args, mesh=self.mesh,
            axes_specs=[*self.param_specs.values(), self.batch_spec],
            param_avals=param_avals, param_specs=param_specs,
            expect_sharded_params=False)

    def _check_comm(self, site, fn, args):
        """Collective-schedule auditor (PADDLE_TPU_COMMCHECK=1): record
        the freshly built program's ordered collective schedule and —
        when a cross-host verifier is attached (init_parallel_env) —
        verify it against the cohort BEFORE the first dispatch, so a
        divergent host dies typed (CollectiveScheduleMismatchError)
        instead of hanging every peer in a collective. Costs one extra
        AOT lower+compile per cold entrypoint; free when off."""
        _cc.check_entrypoint(site, jit_obj=fn, args=args)

    # ---- public step APIs ----------------------------------------------
    def train_batch(self, *batch):
        """Run one optimizer step; returns the (device) loss Tensor."""
        if self.optimizer is None:
            raise RuntimeError(
                "this engine was built without an optimizer; use eval_batch")
        self._adopt_external_writes()
        with _span("engine::device_put"):
            placed = self._place_batch(batch)
        san = _san.enabled()
        cold = self._step_fn is None
        if san:
            # per-call sentinel: the step jit retraces INTERNALLY on any
            # new batch signature — a cache-keyed build hook would miss
            # exactly the silent steady-state recompile this flags
            _san.note_trace(
                "engine.step", self._obs_key,
                (_san.aval_signature(placed), self._san_mesh_sig),
                per_call=True)
        if cold:
            self._step_fn = self._build_step(placed)
        lr = self._lr_scalar()
        key = self._key_scalar()
        step_no = self._step_scalar()
        if cold and _gc.enabled():
            self._audit_graph("engine.step", self._step_fn,
                              (self.param_vals, self.opt_state,
                               self.buffer_vals, placed, lr, key, step_no))
        if cold and _cc.enabled():
            self._check_comm("engine.step", self._step_fn,
                             (self.param_vals, self.opt_state,
                              self.buffer_vals, placed, lr, key, step_no))
        self._step_count += 1
        donated = (self.param_vals, self.opt_state, self.buffer_vals,
                   key, step_no) if san and self.donate else None
        # the hot-sync probe arms only on WARM dispatches: the cold call
        # traces user loss code (compile time, not the hot path)
        self._inflight = ("engine.dispatch", time.monotonic())
        try:
            with _span("engine::dispatch", histogram=self._h_dispatch), \
                    (_san.allow_host_sync("engine.compile") if cold
                     else _san.hot_region("engine.dispatch")):
                (loss, gnorm, self.param_vals, self.opt_state,
                 self.buffer_vals, self._key_dev, self._step_dev) = \
                    self._step_fn(
                        self.param_vals, self.opt_state, self.buffer_vals,
                        placed, lr, key, step_no)
        finally:
            self._inflight = None
        if donated is not None:
            _san.note_donation("engine.dispatch", donated,
                               tag=f"step {self._step_count}")
        self.stats["dispatches"] += 1
        self.stats["steps"] += 1
        self.last_grad_norm = gnorm  # device scalar; float() to read
        self.last_grad_norms = None  # per-step vector: train_batches only
        with _span("engine::write_back"):
            self._write_back_buffers()
        if san:
            # AFTER write-back: a NonFiniteError here is meant to be
            # caught, and the model's buffer Tensors must already point
            # at the post-dispatch values (their old buffers were just
            # donated)
            _san.check_finite("engine.step", self._finite_leaves(
                loss=loss, grad_norm=gnorm))
        # Parameters resolve lazily via their EngineRef — no per-param
        # write-back loop. LR schedulers follow the eager convention: the
        # USER calls scheduler.step(); get_lr() is re-read every batch (the
        # device scalar is refreshed only when the host value changes).
        return Tensor(loss)

    def train_batches(self, batches, n=None):
        """Run up to `n` optimizer micro-steps in ONE XLA dispatch.

        `batches` is an iterable of batch-arg tuples (or single args). All
        micro-steps run inside a `lax.scan`: the step counter, RNG key and
        learning-rate schedule advance on-device, so no host code executes
        between micro-steps. When every element is the *same* batch object
        (e.g. ``[batch] * n``) the fused static variant is used — the batch
        is passed once as a scan-invariant operand instead of stacked.

        If the optimizer's learning rate is an LRScheduler the engine
        advances it once per consumed micro-batch (do NOT also call
        ``scheduler.step()`` for these steps). Returns a device Tensor of
        shape ``(n,)`` with the per-micro-step losses.
        """
        if self.optimizer is None:
            raise RuntimeError(
                "this engine was built without an optimizer; use eval_batch")
        batches = list(batches)
        if n is not None:
            batches = batches[:n]
        if not batches:
            return Tensor(jnp.zeros((0,), jnp.float32))
        n = len(batches)
        static = all(b is batches[0] for b in batches[1:])
        norm = [tuple(b) if isinstance(b, (list, tuple)) else (b,)
                for b in batches]

        self._adopt_external_writes()
        with _span("engine::device_put"):
            if static:
                placed = self._place_batch(norm[0])
            else:
                vals = [tuple(b._value if isinstance(b, Tensor)
                              else jnp.asarray(b) for b in bt)
                        for bt in norm]
                arity = len(vals[0])
                ragged = any(len(bt) != arity for bt in vals) or any(
                    len(set((tuple(bt[j].shape), str(bt[j].dtype))
                            for bt in vals)) > 1
                    for j in range(arity))
                if ragged:
                    # ragged batches can't stack onto a scan axis — fall
                    # back to sequential single-step dispatches, keeping
                    # the train_batches contract: the engine (not the
                    # user) advances an LRScheduler per consumed batch
                    sched = self.optimizer._learning_rate
                    losses, gnorms = [], []
                    for bt in norm:
                        losses.append(self.train_batch(*bt)._value)
                        gnorms.append(self.last_grad_norm)
                        if isinstance(sched, LRScheduler):
                            sched.step()
                    self.last_grad_norms = jnp.stack(gnorms)
                    return Tensor(jnp.stack(losses))
                placed = []
                nputs = 0
                for j in range(len(vals[0])):
                    stacked = jnp.stack([bt[j] for bt in vals])
                    sh = _named_sharding(
                        self.mesh,
                        _stacked_batch_spec(self.batch_spec, stacked.ndim))
                    placed.append(jax.device_put(stacked, sh))
                    nputs += 1
                placed = tuple(placed)
                self.stats["device_puts"] += nputs

        sig = (n, static, tuple((tuple(a.shape), str(a.dtype))
                                for a in placed))
        san = _san.enabled()
        if san:
            _san.note_trace("engine.multi", self._obs_key,
                            (sig, self._san_mesh_sig), per_call=True)
        fn = self._multi_fns.get(sig)
        cold = fn is None
        if cold:
            fn = self._build_multi(placed, static)
            self._multi_fns[sig] = fn

        lrs = self._lr_schedule_array(n)
        key = self._key_scalar()
        step0 = self._step_scalar()
        if cold and _gc.enabled():
            self._audit_graph("engine.multi", fn,
                              (self.param_vals, self.opt_state,
                               self.buffer_vals, placed, lrs, key, step0))
        if cold and _cc.enabled():
            self._check_comm("engine.multi", fn,
                             (self.param_vals, self.opt_state,
                              self.buffer_vals, placed, lrs, key, step0))
        donated = (self.param_vals, self.opt_state, self.buffer_vals,
                   key, step0) if san and self.donate else None
        self._inflight = ("engine.dispatch", time.monotonic())
        try:
            with _span("engine::dispatch", histogram=self._h_dispatch), \
                    (_san.allow_host_sync("engine.compile") if cold
                     else _san.hot_region("engine.dispatch")):
                (losses, gnorms, self.param_vals, self.opt_state,
                 self.buffer_vals, self._key_dev, self._step_dev) = fn(
                    self.param_vals, self.opt_state, self.buffer_vals,
                    placed, lrs, key, step0)
        finally:
            self._inflight = None
        if donated is not None:
            _san.note_donation("engine.dispatch", donated,
                               tag=f"steps {self._step_count + 1}.."
                                   f"{self._step_count + n}")
        self.stats["dispatches"] += 1
        self.stats["steps"] += n
        self._step_count += n
        self.last_grad_norms = gnorms  # (n,) device vector, one per step
        self.last_grad_norm = gnorms[-1]
        with _span("engine::write_back"):
            self._write_back_buffers()
        if san:
            # AFTER write-back — see train_batch
            _san.check_finite("engine.step", self._finite_leaves(
                loss=losses, grad_norm=gnorms))
        return Tensor(losses)

    def _lr_schedule_array(self, n):
        """(n,) device lr values for the next n micro-steps. Plain-float
        learning rates are cached per (n, value); an LRScheduler is
        evaluated AND advanced host-side once per micro-step (the schedule
        values then ride into the compiled scan as xs)."""
        sched = self.optimizer._learning_rate
        if not isinstance(sched, LRScheduler):
            lr = float(sched)
            if self._lrs_key != (n, lr):
                self._lrs_key = (n, lr)
                self._lrs_dev = jax.device_put(
                    jnp.full((n,), lr, jnp.float32), self._scalar_sh)
                self.stats["device_puts"] += 1
            return self._lrs_dev
        vals = np.empty((n,), np.float32)
        for i in range(n):
            vals[i] = float(sched())
            sched.step()
        arr = jax.device_put(jnp.asarray(vals), self._scalar_sh)
        self.stats["device_puts"] += 1
        return arr

    def _finite_leaves(self, **scalars):
        """(path, value) sweep order for the tpu-san non-finite guard:
        loss and grad norm first (cheapest, most diagnostic), then every
        parameter — so the blame names the first poisoned param path."""
        leaves = list(scalars.items())
        leaves.extend(("param/" + n, v) for n, v in self.param_vals.items())
        return leaves

    def _write_back_buffers(self):
        for n, b in self._buffers.items():
            b._value = self.buffer_vals[n]

    def eval_batch(self, *batch):
        """Jitted loss evaluation (no grads, no update). Shares the cached
        batch-placement helper and shardings with the train path."""
        self._adopt_external_writes()
        with _span("engine::device_put"):
            placed = self._place_batch(batch)
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in placed)
        if _san.enabled():
            _san.note_trace("engine.eval", self._obs_key,
                            (sig, self._san_mesh_sig), per_call=True)
        fn = self._eval_fns.get(sig)
        cold = fn is None
        if cold:
            fn = self._build_eval(placed)
            self._eval_fns[sig] = fn
        key = rng_mod.next_key()
        if cold and _gc.enabled():
            self._audit_graph("engine.eval", fn,
                              (self.param_vals, self.buffer_vals, placed,
                               key))
        if cold and _cc.enabled():
            self._check_comm("engine.eval", fn,
                             (self.param_vals, self.buffer_vals, placed,
                              key))
        with _span("engine::dispatch", histogram=self._h_dispatch), \
                (_san.allow_host_sync("engine.compile") if cold
                 else _san.hot_region("engine.dispatch")):
            loss = fn(self.param_vals, self.buffer_vals, placed, key)
        self.stats["dispatches"] += 1
        if _san.enabled():
            _san.check_finite("engine.eval", [("loss", loss)])
        return Tensor(loss)

    def _build_eval(self, batch_avals):
        apply_fn = self._apply
        compute_dtype = self.compute_dtype
        cp_guard = self._cp_guard

        def ev(params, buffers, batch, key):
            if compute_dtype is not None:
                params = {n: (v.astype(compute_dtype) if _is_float(v)
                              else v) for n, v in params.items()}
            rng_mod.push_trace_key(key)
            try:
                with cp_guard():
                    loss, _ = apply_fn(params, buffers,
                                       *[Tensor(b) for b in batch])
            finally:
                rng_mod.pop_trace_key()
            return loss

        batch_sh = tuple(self._batch_sharding(a.ndim) for a in batch_avals)
        return jax.jit(
            ev,
            in_shardings=(self._param_sh, self._buf_sh, batch_sh,
                          self._scalar_sh),
            out_shardings=self._scalar_sh,
        )

    def sync_optimizer_state(self):
        """Write engine opt slots back into the eager Optimizer (for
        state_dict parity)."""
        for n, p in self._params.items():
            self.optimizer._accumulators[id(p)] = dict(self.opt_state[n])
        self.optimizer._step_count = self._step_count

    # ---- fault tolerance: snapshots + checkpoint state -----------------
    def _copy_tree(self, d):
        # jnp.copy dispatches a device-side copy that preserves sharding;
        # plain references would be invalidated by the NEXT dispatch (the
        # engine donates params/slots/buffers/key/step to XLA every step)
        return {k: jnp.copy(v) for k, v in d.items()}

    def snapshot(self):
        """Donation-safe deep copy of the engine's carried train state
        (params, optimizer slots, buffers, step count, RNG key) — the unit
        of `train_guard.TrainGuard`'s rollback ring. The RNG key is
        materialized first so a restore replays the EXACT key sequence
        (bit-identical skip-and-continue) instead of redrawing."""
        if self.optimizer is not None:
            self._key_scalar()
        return {
            "step_count": self._step_count,
            "params": self._copy_tree(self.param_vals),
            "opt": {n: self._copy_tree(s)
                    for n, s in self.opt_state.items()},
            "buffers": self._copy_tree(self.buffer_vals),
            "key": None if self._key_dev is None else jnp.copy(
                self._key_dev),
            "key_epoch": self._key_epoch,
        }

    def restore(self, snap):
        """Rewind the engine to `snap` (from `snapshot()`). The snapshot
        itself is copied on the way in, so the SAME snapshot can absorb a
        second rollback. External Parameter writes since the snapshot are
        dropped (the refs are re-armed) — a rollback rewinds everything."""
        self.param_vals = self._copy_tree(snap["params"])
        self.opt_state = {n: self._copy_tree(s)
                          for n, s in snap["opt"].items()}
        self.buffer_vals = self._copy_tree(snap["buffers"])
        self._step_count = int(snap["step_count"])
        self._step_dev = None     # rebuilt from _step_count on next step
        self._key_epoch = snap["key_epoch"]
        self._key_dev = None if snap["key"] is None else jnp.copy(
            snap["key"])
        self._write_back_buffers()
        for _n, p, ref in self._param_refs:
            p._v_ = ref

    def state_dict(self):
        """Checkpointable state tree (Tensor leaves + the step scalar) for
        `CheckpointManager` round-trips: restore_latest() into this tree,
        then `load_state_dict` it back — the engine-level resume path the
        fault-tolerance layer (preemption saves, elastic relaunch) uses."""
        tree = {
            "model": {n: Tensor(v) for n, v in self.param_vals.items()},
            "buffers": {n: Tensor(v) for n, v in self.buffer_vals.items()},
            "step": self._step_count,
        }
        if self.opt_state:
            tree["opt"] = {n: {s: Tensor(v) for s, v in slots.items()}
                           for n, slots in self.opt_state.items()}
        return tree

    def load_state_dict(self, tree):
        """Adopt a `state_dict()`-shaped tree (fresh from a checkpoint
        restore) as the engine's carried state, re-placed per the CURRENT
        mesh shardings."""
        for n in self.param_vals:
            self.param_vals[n] = jax.device_put(
                tree["model"][n]._value, self._param_sh[n])
        for n in self.buffer_vals:
            if n in tree.get("buffers", {}):
                self.buffer_vals[n] = jax.device_put(
                    tree["buffers"][n]._value, self._buf_sh[n])
        for n, slots in (tree.get("opt") or {}).items():
            sh = self._state_sh[n]
            for s, v in slots.items():
                self.opt_state[n][s] = jax.device_put(v._value, sh)
        self._step_count = int(tree.get("step", 0))
        self._step_dev = None
        self._write_back_buffers()
        for _n, p, ref in self._param_refs:
            p._v_ = ref


def parallelize(model, optimizer=None, loss_fn=None, *, mesh=None,
                sharding_stage=0, rules=None, compute_dtype=None,
                context_parallel="ring"):
    """High-level entry (≈ dist.parallelize / fleet.distributed_model +
    distributed_optimizer in one): returns a ShardedTrainStep.

    `mesh` may be a built `jax.sharding.Mesh` OR a declarative
    `sharding.MeshConfig` — `MeshConfig(fsdp=N)` is the one-config pod
    training story (docs/sharding.md): params and optimizer state shard
    along the fsdp axis, gathered in-graph at use sites, with zero
    per-model spec tables."""
    from ..sharding import MeshConfig

    if isinstance(mesh, MeshConfig):
        mesh = mesh.build()
    hcg = None
    if mesh is not None:
        hcg = topo_mod.HybridCommunicateGroup(mesh=mesh)
        topo_mod.set_hybrid_communicate_group(hcg)
    return ShardedTrainStep(model, optimizer, loss_fn=loss_fn, hcg=hcg,
                            sharding_stage=sharding_stage, rules=rules,
                            compute_dtype=compute_dtype,
                            context_parallel=context_parallel)
