"""Sharded, fully-jitted hybrid-parallel train step.

Reference analogs, collapsed into one component:
- `fleet.distributed_model` wrapper selection (fleet/model.py:32)
- EagerReducer fused grad allreduce (collective/reducer.cc:1067)
- DygraphShardingOptimizer / GroupShardedStage2/3 (ZeRO 1/2/3)
- HybridParallelOptimizer grad-clip-across-groups
  (hybrid_parallel_optimizer.py:254)
- static-graph Engine._parallel (auto_parallel/static/engine.py:764)

TPU-native design: ONE jitted program per training step. Parameters,
optimizer slots and the batch carry NamedShardings over the hybrid mesh
(dp/pp/sharding/sep/mp); XLA/GSPMD then *derives* every collective the
reference implements imperatively: grad all-reduce over dp (reducer),
all-gather of ZeRO-sharded params before use + reduce-scatter of grads
(stages 1-3), mp all-reduces inside TP blocks. Buffers are donated so
parameter memory updates in place in HBM.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue
from ..ops import random as rng_mod
from .functional import functionalize
from .sharding_spec import (
    DEFAULT_TP_RULES, spec_for_param, opt_state_spec,
)
from . import topology as topo_mod


def _is_float(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def _clip_grads(grads, clip):
    """Functional grad clip (reference: ClipGradByGlobalNorm nn/clip.py,
    applied across all hybrid groups by HybridParallelOptimizer — here grads
    are already global values, so one global norm is THE cross-group norm)."""
    if clip is None:
        return grads
    if isinstance(clip, ClipGradByGlobalNorm):
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in grads.values()))
        scale = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(norm, 1e-12))
        return dict((k, (g.astype(jnp.float32) * scale).astype(g.dtype))
                           for k, g in grads.items())
    if isinstance(clip, ClipGradByNorm):
        out = {}
        for k, g in grads.items():
            n = jnp.linalg.norm(g.astype(jnp.float32).reshape(-1))
            s = jnp.minimum(1.0, clip.clip_norm / jnp.maximum(n, 1e-12))
            out[k] = (g.astype(jnp.float32) * s).astype(g.dtype)
        return out
    if isinstance(clip, ClipGradByValue):
        return dict(
            (k, jnp.clip(g, clip.min, clip.max)) for k, g in grads.items())
    return grads


class ShardedTrainStep:
    """Compile `loss_fn(model, *batch)` + optimizer update into one sharded
    XLA program over the hybrid mesh."""

    def __init__(self, model, optimizer, loss_fn=None, hcg=None,
                 sharding_stage=0, rules=None, compute_dtype=None,
                 batch_spec=None, donate=True, context_parallel="ring"):
        self.model = model
        self.optimizer = optimizer
        self.hcg = hcg or topo_mod.get_hybrid_communicate_group()
        if self.hcg is None:
            self.hcg = topo_mod.HybridCommunicateGroup(
                mesh=topo_mod.build_mesh(dp=-1))
            topo_mod.set_hybrid_communicate_group(self.hcg)
        self.mesh = self.hcg.mesh
        self.sharding_stage = sharding_stage
        self.rules = DEFAULT_TP_RULES if rules is None else rules
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self.donate = donate
        # context-parallel attention over the sep axis ("ring" | "ulysses" |
        # None); model-level sdpa calls reroute inside the traced step.
        self.context_parallel = context_parallel

        if loss_fn is None:
            if not hasattr(model, "loss"):
                raise ValueError("pass loss_fn or give the model a .loss")
            loss_fn = lambda m, *batch: m.loss(*batch)  # noqa: E731
        self._apply, self._params, self._buffers = functionalize(
            model, method=lambda *b: loss_fn(model, *b))

        # ---- shardings -------------------------------------------------
        mesh = self.mesh
        self.param_specs = dict(
            (n, spec_for_param(n, p, self.rules,
                               sharding_stage=sharding_stage, mesh=mesh))
            for n, p in self._params.items())
        self.state_specs = dict(
            (n, opt_state_spec(self.param_specs[n], p.shape, mesh,
                               sharding_stage=sharding_stage))
            for n, p in self._params.items())
        # batch: dim0 over the fused data axes (dp+sharding, the reference
        # fuses them for grad sync, topology.py:228); dim1 (sequence) over
        # sep when in use.
        if batch_spec is None:
            entries = [("dp", "sharding")]
            if mesh.shape["sep"] > 1:
                entries.append("sep")
            batch_spec = P(*entries)
        self.batch_spec = batch_spec

        # ---- place values ---------------------------------------------
        self.param_vals = {}
        for n, p in self._params.items():
            sh = NamedSharding(mesh, self.param_specs[n])
            p._value = jax.device_put(p._value, sh)
            self.param_vals[n] = p._value
        self.buffer_vals = {}
        for n, b in self._buffers.items():
            sh = NamedSharding(mesh, P(*([None] * b.ndim)))
            b._value = jax.device_put(b._value, sh)
            self.buffer_vals[n] = b._value

        # optimizer slots, sharded per state_specs (None optimizer = eval-only
        # engine; train_batch will refuse)
        self.opt_state = {}
        if self.optimizer is not None:
            for n, p in self._params.items():
                names = self.optimizer._state_names
                sh = NamedSharding(mesh, self.state_specs[n])
                self.opt_state[n] = {
                    s: jax.device_put(jnp.zeros(p.shape, p.dtype), sh)
                    for s in names}

        self._step_fn = None
        self._eval_fn = None
        self._step_count = 0
        self.last_grad_norm = None

    # ------------------------------------------------------------------
    def _cp_guard(self):
        """Context manager enabling context-parallel attention during trace
        (no-op when sep == 1 or context_parallel=None)."""
        import contextlib
        if not self.context_parallel or self.mesh.shape["sep"] <= 1:
            return contextlib.nullcontext()
        from .context_parallel import context_parallel_guard
        return context_parallel_guard(self.mesh, mode=self.context_parallel)

    def _build_step(self, batch_avals):
        mesh = self.mesh
        apply_fn = self._apply
        opt = self.optimizer
        clip = getattr(opt, "_grad_clip", None)
        compute_dtype = self.compute_dtype

        cp_guard = self._cp_guard

        def loss_of(params, buffers, batch, key):
            if compute_dtype is not None:
                params = {n: (v.astype(compute_dtype) if _is_float(v) else v)
                          for n, v in params.items()}
                # float batch inputs (images, features) join the compute
                # dtype too — conv/matmul require matching operand dtypes
                batch = tuple(b.astype(compute_dtype) if _is_float(b) else b
                              for b in batch)
            rng_mod.push_trace_key(key)
            try:
                with cp_guard():
                    loss, new_buf = apply_fn(params, buffers, *[
                        Tensor(b) for b in batch])
            finally:
                rng_mod.pop_trace_key()
            return loss, new_buf

        def step(params, opt_state, buffers, batch, key, lr, step_no):
            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, buffers, batch, key)
            grads = dict(
                (n, g.astype(params[n].dtype)) for n, g in grads.items())
            # pre-clip global grad norm, exposed for parity/diagnostics
            # (sharding bugs show up in the grad-norm trajectory steps before
            # they move the loss); XLA CSEs this with the clip's own norm
            gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                 for g in grads.values()))
            grads = _clip_grads(grads, clip)
            new_params = {}
            new_state = {}
            for n, p in params.items():
                np_, ns = opt._update_one(p, grads[n], opt_state[n], lr,
                                          step_no)
                new_params[n] = np_
                new_state[n] = ns
            return loss, gnorm, new_params, new_state, new_buf

        param_sh = {n: NamedSharding(mesh, s)
                    for n, s in self.param_specs.items()}
        state_sh = {n: {s: NamedSharding(mesh, self.state_specs[n])
                        for s in self.opt_state[n]}
                    for n in self.opt_state}
        buf_sh = {n: NamedSharding(mesh, P(*([None] * v.ndim)))
                  for n, v in self.buffer_vals.items()}
        batch_sh = tuple(
            NamedSharding(mesh, self._batch_spec_for(a.ndim))
            for a in batch_avals)
        scalar_sh = NamedSharding(mesh, P())

        return jax.jit(
            step,
            in_shardings=(param_sh, state_sh, buf_sh, batch_sh, scalar_sh,
                          scalar_sh, scalar_sh),
            out_shardings=(scalar_sh, scalar_sh, param_sh, state_sh, buf_sh),
            donate_argnums=(0, 1, 2) if self.donate else (),
        )

    def _batch_spec_for(self, ndim):
        spec = list(self.batch_spec)[:ndim]
        spec += [None] * (ndim - len(spec))
        return P(*spec)

    def train_batch(self, *batch):
        """Run one optimizer step; returns the (device) loss Tensor."""
        if self.optimizer is None:
            raise RuntimeError(
                "this engine was built without an optimizer; use eval_batch")
        batch_vals = tuple(
            b._value if isinstance(b, Tensor) else jnp.asarray(b)
            for b in batch)
        placed = tuple(
            jax.device_put(v, NamedSharding(self.mesh,
                                            self._batch_spec_for(v.ndim)))
            for v in batch_vals)
        if self._step_fn is None:
            self._step_fn = self._build_step(placed)
        self._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self._step_count, jnp.int32)
        key = rng_mod.next_key()
        loss, gnorm, self.param_vals, self.opt_state, self.buffer_vals = \
            self._step_fn(self.param_vals, self.opt_state, self.buffer_vals,
                          placed, key, lr, step_no)
        self.last_grad_norm = gnorm  # device scalar; float() to read
        # keep live Parameter objects pointing at current values so eager
        # reads (state_dict, debugging) stay correct without copies
        for n, p in self._params.items():
            p._value = self.param_vals[n]
        for n, b in self._buffers.items():
            b._value = self.buffer_vals[n]
        # LR schedulers follow the eager convention: the USER calls
        # scheduler.step(); get_lr() is re-read (host-side) every batch.
        return Tensor(loss)

    def eval_batch(self, *batch):
        """Jitted loss evaluation (no grads, no update)."""
        batch_vals = tuple(
            b._value if isinstance(b, Tensor) else jnp.asarray(b)
            for b in batch)
        placed = tuple(
            jax.device_put(v, NamedSharding(self.mesh,
                                            self._batch_spec_for(v.ndim)))
            for v in batch_vals)
        if self._eval_fn is None:
            apply_fn = self._apply
            compute_dtype = self.compute_dtype

            cp_guard = self._cp_guard

            def ev(params, buffers, batch, key):
                if compute_dtype is not None:
                    params = {n: (v.astype(compute_dtype) if _is_float(v)
                                  else v) for n, v in params.items()}
                rng_mod.push_trace_key(key)
                try:
                    with cp_guard():
                        loss, _ = apply_fn(params, buffers,
                                           *[Tensor(b) for b in batch])
                finally:
                    rng_mod.pop_trace_key()
                return loss

            self._eval_fn = jax.jit(ev)
        key = rng_mod.next_key()
        return Tensor(self._eval_fn(self.param_vals, self.buffer_vals,
                                    placed, key))

    def sync_optimizer_state(self):
        """Write engine opt slots back into the eager Optimizer (for
        state_dict parity)."""
        for n, p in self._params.items():
            self.optimizer._accumulators[id(p)] = dict(self.opt_state[n])
        self.optimizer._step_count = self._step_count


def parallelize(model, optimizer=None, loss_fn=None, *, mesh=None,
                sharding_stage=0, rules=None, compute_dtype=None,
                context_parallel="ring"):
    """High-level entry (≈ dist.parallelize / fleet.distributed_model +
    distributed_optimizer in one): returns a ShardedTrainStep."""
    hcg = None
    if mesh is not None:
        hcg = topo_mod.HybridCommunicateGroup(mesh=mesh)
        topo_mod.set_hybrid_communicate_group(hcg)
    return ShardedTrainStep(model, optimizer, loss_fn=loss_fn, hcg=hcg,
                            sharding_stage=sharding_stage, rules=rules,
                            compute_dtype=compute_dtype,
                            context_parallel=context_parallel)
