"""Reference: python/paddle/distributed/io.py — persistable save/load
helpers for distributed jobs (thin over the framework io here)."""
from __future__ import annotations

from ..core.tensor import Tensor

__all__ = ["is_persistable", "save_persistables", "load_persistables"]


def is_persistable(var):
    return isinstance(var, Tensor) and not var.stop_gradient


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import save, default_main_program
    save(main_program or default_main_program(),
         f"{dirname}/{filename or 'persistables'}")


def load_persistables(executor, dirname, main_program=None, filename=None):
    from ..static import load, default_main_program
    load(main_program or default_main_program(),
         f"{dirname}/{filename or 'persistables'}")
