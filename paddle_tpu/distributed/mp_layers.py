"""Tensor-(model-)parallel layers.

Reference analog: fleet/layers/mpu/mp_layers.py — `VocabParallelEmbedding`
(:47), `ColumnParallelLinear` (:333), `RowParallelLinear` (:540),
`ParallelCrossEntropy` (:741), with hand-written identity/allreduce/
split-concat comm ops (mpu/mp_ops.py).

TPU-native redesign: each layer stores the FULL logical weight and attaches
*logical axis names* (`param.logical_axes`, e.g. ("embed", "mlp")). When
fleet/the engine places parameters (sharding_spec.shard_params /
device_put), the `paddle_tpu.sharding` rule table resolves those names
onto whatever mesh is active — "mp" on the hybrid training topology, "tp"
on a MeshConfig serving mesh — and the weight physically shards across
that ring; the forward is ordinary dense math plus *logical* sharding
constraints — GSPMD inserts exactly the all-reduce / all-gather the
reference codes by hand, fused into the surrounding matmuls. No special
backward is needed: differentiating through a constraint yields the dual
collective (identity↔psum), the same pairing mp_ops.py implements
manually.
"""
from __future__ import annotations

from .. import nn
from ..nn import functional as F
from ..sharding import with_logical_constraint


class ColumnParallelLinear(nn.Layer):
    """y = x @ W[:, shard] (+b). Weight [in, out] column-sharded over the
    tensor-parallel axis (logical out axis "mlp" by default; pass
    `logical_axes` to tag attention projections as "heads")."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, logical_axes=("embed", "mlp")):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self._out_axis = logical_axes[-1]
        self.linear.weight.logical_axes = tuple(logical_axes)
        self.linear.weight.is_distributed = True
        if self.linear.bias is not None:
            self.linear.bias.logical_axes = (self._out_axis,)
            self.linear.bias.is_distributed = True
        self.gather_output = gather_output

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        # replicate input along the tp axis (the reference's _c_identity),
        # compute, leave output tp-sharded on the feature dim unless
        # gather_output.
        y = self.linear(x)
        ndim = y.ndim
        if self.gather_output:
            y = with_logical_constraint(y, *([None] * ndim))
        else:
            y = with_logical_constraint(
                y, *([None] * (ndim - 1)), self._out_axis)
        return y


class RowParallelLinear(nn.Layer):
    """y = sum_over_shards(x_shard @ W[shard, :]) (+b). Weight [in, out]
    row-sharded; input expected feature-sharded when input_is_parallel."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None,
                 logical_axes=("mlp", "embed")):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self._in_axis = logical_axes[0]
        self.linear.weight.logical_axes = tuple(logical_axes)
        self.linear.weight.is_distributed = True
        self.input_is_parallel = input_is_parallel

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        if self.input_is_parallel:
            x = with_logical_constraint(
                x, *([None] * (x.ndim - 1)), self._in_axis)
        y = self.linear(x)
        # contraction over the sharded dim leaves a partial sum; constraining
        # the output replicated forces the psum (reference: mp_allreduce).
        return with_logical_constraint(y, *([None] * y.ndim))


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dim sharded over the tp axis
    (mp_layers.py:47)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.embedding = nn.Embedding(num_embeddings, embedding_dim,
                                      weight_attr=weight_attr)
        self.embedding.weight.logical_axes = ("vocab", "embed")
        self.embedding.weight.is_distributed = True

    @property
    def weight(self):
        return self.embedding.weight

    def forward(self, x):
        y = self.embedding(x)
        return with_logical_constraint(y, *([None] * y.ndim))


class ParallelCrossEntropy(nn.Layer):
    """Cross entropy over tp-sharded vocab logits (mp_layers.py:741). The
    log-sum-exp over the sharded class dim compiles to a tp psum."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = with_logical_constraint(
            input, *([None] * (input.ndim - 1)), "vocab")
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
