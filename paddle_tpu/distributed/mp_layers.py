"""Tensor-(model-)parallel layers.

Reference analog: fleet/layers/mpu/mp_layers.py — `VocabParallelEmbedding`
(:47), `ColumnParallelLinear` (:333), `RowParallelLinear` (:540),
`ParallelCrossEntropy` (:741), with hand-written identity/allreduce/
split-concat comm ops (mpu/mp_ops.py).

TPU-native redesign: each layer stores the FULL logical weight and attaches
a `dist_spec` (PartitionSpec over the 'mp' mesh axis). When fleet/the engine
places parameters (sharding_spec.shard_params / device_put), the weight
physically shards across the mp ring; the forward is ordinary dense math
plus sharding *constraints* — GSPMD inserts exactly the all-reduce /
all-gather the reference codes by hand, fused into the surrounding matmuls.
No special backward is needed: differentiating through a constraint yields
the dual collective (identity↔psum), the same pairing mp_ops.py implements
manually.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from .. import nn
from ..nn import functional as F
from .. import ops
from .sharding_spec import shard_constraint


class ColumnParallelLinear(nn.Layer):
    """y = x @ W[:, shard] (+b). Weight [in, out] column-sharded over mp."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.linear.weight.dist_spec = P(None, "mp")
        self.linear.weight.is_distributed = True
        if self.linear.bias is not None:
            self.linear.bias.dist_spec = P("mp")
            self.linear.bias.is_distributed = True
        self.gather_output = gather_output

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        # replicate input along mp (the reference's _c_identity), compute,
        # leave output mp-sharded on the feature dim unless gather_output.
        y = self.linear(x)
        ndim = y.ndim
        if self.gather_output:
            y = shard_constraint(y, *([None] * ndim))
        else:
            y = shard_constraint(y, *([None] * (ndim - 1) + ["mp"]))
        return y


class RowParallelLinear(nn.Layer):
    """y = sum_over_shards(x_shard @ W[shard, :]) (+b). Weight [in, out]
    row-sharded; input expected feature-sharded when input_is_parallel."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.linear.weight.dist_spec = P("mp", None)
        self.linear.weight.is_distributed = True
        self.input_is_parallel = input_is_parallel

    @property
    def weight(self):
        return self.linear.weight

    @property
    def bias(self):
        return self.linear.bias

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_constraint(x, *([None] * (x.ndim - 1) + ["mp"]))
        y = self.linear(x)
        # contraction over the sharded dim leaves a partial sum; constraining
        # the output replicated forces the psum (reference: mp_allreduce).
        return shard_constraint(y, *([None] * y.ndim))


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dim sharded over mp (mp_layers.py:47)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.embedding = nn.Embedding(num_embeddings, embedding_dim,
                                      weight_attr=weight_attr)
        self.embedding.weight.dist_spec = P("mp", None)
        self.embedding.weight.is_distributed = True

    @property
    def weight(self):
        return self.embedding.weight

    def forward(self, x):
        y = self.embedding(x)
        return shard_constraint(y, *([None] * y.ndim))


class ParallelCrossEntropy(nn.Layer):
    """Cross entropy over mp-sharded vocab logits (mp_layers.py:741). The
    log-sum-exp over the sharded class dim compiles to an mp psum."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        input = shard_constraint(
            input, *([None] * (input.ndim - 1) + ["mp"]))
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
