"""paddle.distributed.stream.* — stream-addressed collective variants.

Reference: python/paddle/distributed/communication/stream/ (all_reduce.py
etc.), whose extra knob is `use_calc_stream` — run the collective on the
compute stream instead of the comm stream to skip an event sync.

TPU-native: XLA owns stream assignment; a compiled collective is already
scheduled on whichever stream the fusion lands on, so `use_calc_stream`
has no independent meaning and every variant delegates to the eager API.
The surface exists so reference call sites run unmodified.
"""
from __future__ import annotations

from . import collective as _C
from .p2p import gather as _gather, reduce as _reduce
from .p2p import recv as _recv, send as _send


def all_reduce(tensor, op=None, group=None, sync_op=True,
               use_calc_stream=False):
    return _C.all_reduce(tensor, op if op is not None else _C.ReduceOp.SUM,
                         group=group, sync_op=sync_op)


def all_gather(tensor_or_tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    return _C.all_gather(tensor_or_tensor_list, tensor, group=group,
                         sync_op=sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    return _C.broadcast(tensor, src=src, group=group, sync_op=sync_op)


def reduce_scatter(tensor, tensor_or_tensor_list=None, op=None, group=None,
                   sync_op=True, use_calc_stream=False):
    return _C.reduce_scatter(tensor, tensor_or_tensor_list,
                             op if op is not None else _C.ReduceOp.SUM,
                             group=group, sync_op=sync_op)


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True,
             use_calc_stream=False):
    return _C.all_to_all(out_tensor_list, in_tensor_list, group=group,
                         sync_op=sync_op)


def scatter(tensor, tensor_or_tensor_list=None, src=0, group=None,
            sync_op=True, use_calc_stream=False):
    return _C.scatter(tensor, tensor_or_tensor_list, src=src, group=group,
                      sync_op=sync_op)


def reduce(tensor, dst=0, op=None, group=None, sync_op=True,
           use_calc_stream=False):
    return _reduce(tensor, dst=dst, op=op, group=group, sync_op=sync_op)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True,
           use_calc_stream=False):
    return _gather(tensor, gather_list=gather_list, dst=dst, group=group,
                   sync_op=sync_op)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _send(tensor, dst=dst, group=group, sync_op=sync_op)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _recv(tensor, src=src, group=group, sync_op=sync_op)
