"""Bounded background host->device prefetch.

Reference analog: the pin-memory + double-buffer DataLoader readers
(reader.py `use_buffer_reader`) that overlap H2D copies with compute.
TPU-native shape: a single daemon thread (io.PrefetchThread) runs
`jax.device_put` (sharded over the training mesh) `size` batches ahead of
consumption, so the transfer of batch k+1 overlaps the compiled step of
batch k. Ordering is FIFO; errors from the source iterator propagate to
the consumer at the position they occurred; `close()` (or exhaustion)
joins the thread — no leaks.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..io import PrefetchThread
# the batch layout is owned by paddle_tpu.sharding (deduplicated from the
# engine's former per-ndim helpers) so standalone placement matches the
# engine's exactly
from ..sharding import (
    batch_spec_for_ndim, default_batch_spec,
    named_sharding as _named_sharding,
)

__all__ = ["DevicePrefetcher", "prefetch_to_device"]


class DevicePrefetcher:
    """Iterator wrapper: sharded device_put runs `size` items ahead in a
    daemon thread. Yields the input pytree structure with leaves as device
    Tensors. See `prefetch_to_device`."""

    def __init__(self, iterator, mesh=None, size=2, spec=None, engine=None):
        self._engine = engine
        self._mesh = mesh if mesh is not None else getattr(
            engine, "mesh", None)
        self._spec = spec
        self._sh_cache = {}
        self.stats = {"batches": 0, "device_puts": 0}
        self._impl = PrefetchThread(iter(iterator), transform=self._place,
                                    depth=size,
                                    name="paddle-tpu-device-prefetch")
        self._t = self._impl._t

    # -- placement -------------------------------------------------------
    def _sharding(self, ndim):
        sh = self._sh_cache.get(ndim)
        if sh is not None:
            return sh
        if self._engine is not None:
            # share the engine's cached per-ndim batch shardings so the
            # engine's placement check passes values through untouched
            sh = self._engine._batch_sharding(ndim)
        elif self._mesh is not None:
            spec = self._spec if self._spec is not None \
                else default_batch_spec(self._mesh)
            sh = _named_sharding(self._mesh, batch_spec_for_ndim(spec, ndim))
        else:
            sh = None  # default device placement
        self._sh_cache[ndim] = sh
        return sh

    def _place_leaf(self, v):
        if isinstance(v, Tensor):
            v = v._value
        if not isinstance(v, jax.Array):
            v = np.asarray(v)
        sh = self._sharding(v.ndim)
        if sh is None:
            out = jnp.asarray(v)
        elif getattr(v, "sharding", None) == sh:
            return Tensor(v)
        else:
            out = jax.device_put(v, sh)
        self.stats["device_puts"] += 1
        return Tensor(out)

    def _place(self, item):
        return jax.tree_util.tree_map(self._place_leaf, item)

    # -- consumer --------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        item = self._impl.get()
        self.stats["batches"] += 1
        return item

    @property
    def consumed(self):
        """Batches handed to the consumer — NOT batches pulled from the
        source (the worker runs `size` ahead). This is the position a
        bit-exact data-resume checkpoint must record: feed it to
        `DataLoader.state_dict(consumed=...)`."""
        return self.stats["batches"]

    def close(self):
        """Stop the worker and join it; safe to call more than once. In-
        flight prefetched batches are dropped."""
        self._impl.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # best-effort; explicit close() is the contract
        try:
            self.close()
        except Exception:  # tpu-lint: disable=TL007 — interpreter teardown
            pass


def prefetch_to_device(iterator, mesh=None, size=2, spec=None, engine=None):
    """Wrap `iterator` so host->device transfer runs `size` batches ahead
    of consumption in a background thread (transfer/compute overlap — the
    TPU-native role of the reference's pin-memory double-buffer readers).

    Each yielded item keeps its pytree structure (tuple/list/dict) with
    leaves converted to device Tensors:

    - `engine=` (a ShardedTrainStep): leaves are placed with the engine's
      own cached per-ndim batch NamedShardings, so `train_batch` /
      `train_batches` pass them through with zero further transfers.
    - `mesh=` (+ optional `spec`): sharded `device_put` with the engine's
      default batch layout (`engine.default_batch_spec`).
    - neither: plain transfer to the default device.

    Returns a `DevicePrefetcher` — a closeable iterator. Iterate it to
    exhaustion or call `.close()` (it is also a context manager); both
    join the worker thread.
    """
    return DevicePrefetcher(iterator, mesh=mesh, size=size, spec=spec,
                            engine=engine)
