"""Eager point-to-point communication.

Reference analog: python/paddle/distributed/communication/send.py /
recv.py / batch_isend_irecv.py / reduce.py / gather.py (backed by NCCL
send/recv, paddle/fluid/distributed/collective/process_group_nccl.cc).

TPU-native stance: *compiled* p2p is `lax.ppermute` inside shard_map /
the pipeline schedules — that is the performance path and what the
framework's own PP/CP layers use. These eager APIs exist for the
reference's debugging/utility workflows (parameter surgery, custom
bootstrap exchanges) and are host-mediated: on a launched multi-process
job the payload moves through the native coordination store
(native/coord_store.cc) over DCN; in a single process a local mailbox
gives the same ordered-pair semantics with world-of-one ranks.
"""
from __future__ import annotations

import collections
import pickle
import threading

import numpy as np
import jax.numpy as jnp

from ..analysis import locks as _locks
from ..core.tensor import Tensor
from .env import get_rank, get_world_size, get_store


class _LocalMailbox:
    """Ticketed (src, dst) channels inside one process: each send gets a
    monotonically increasing index, each receive reserves the next index
    up front — concurrent irecv threads therefore consume messages in
    posting order, never racing for the same payload."""

    def __init__(self):
        self._items = collections.defaultdict(dict)  # (src,dst) -> {idx: v}
        self._push = collections.defaultdict(int)
        self._cv = _locks.new_condition("p2p.mailbox")

    def put(self, src, dst, payload):
        with self._cv:
            idx = self._push[(src, dst)]
            self._push[(src, dst)] = idx + 1
            self._items[(src, dst)][idx] = payload
            self._cv.notify_all()

    def get(self, src, dst, ticket, timeout=None):
        with self._cv:
            ok = self._cv.wait_for(
                lambda: ticket in self._items[(src, dst)], timeout=timeout)
            if not ok:
                raise TimeoutError(
                    f"recv from rank {src} timed out after {timeout}s")
            return self._items[(src, dst)].pop(ticket)


_mailbox = _LocalMailbox()
_seq_lock = _locks.new_lock("p2p.seq")
_send_seq = collections.defaultdict(int)   # (src, dst) -> next seq to send
_recv_seq = collections.defaultdict(int)   # (src, dst) -> next seq to take


def _reserve_recv(src, dst):
    """Atomically claim the next receive slot for the (src, dst) channel —
    called on the POSTING thread so two concurrent irecvs keep order."""
    with _seq_lock:
        seq = _recv_seq[(src, dst)]
        _recv_seq[(src, dst)] = seq + 1
    return seq


def _unreserve_recv(src, dst, ticket):
    """Roll back a reservation whose wait timed out, so the channel does
    not desync. Only possible while it is still the most recent claim."""
    with _seq_lock:
        if _recv_seq[(src, dst)] == ticket + 1:
            _recv_seq[(src, dst)] = ticket
            return True
    return False


def _reset_p2p_state():
    global _mailbox
    _mailbox = _LocalMailbox()
    with _seq_lock:
        _send_seq.clear()
        _recv_seq.clear()


def _to_numpy(tensor):
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    return np.asarray(v)


def _assign(tensor, arr):
    if isinstance(tensor, Tensor):
        tensor._value = jnp.asarray(arr)
        return tensor
    return Tensor(jnp.asarray(arr))


class P2PTask:
    """Completed-or-joinable work handle (reference: distributed Task/Work
    objects returned by isend/irecv)."""

    def __init__(self, thread=None, result_box=None, tensor=None):
        self._thread = thread
        self._box = result_box
        self._tensor = tensor

    def wait(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                raise TimeoutError("p2p task did not complete in time")
            self._thread = None
            if self._box is not None:
                err, arr = self._box
                if err is not None:
                    raise err
                if self._tensor is not None and arr is not None:
                    _assign(self._tensor, arr)
        return True

    def is_completed(self):
        return self._thread is None or not self._thread.is_alive()


def send(tensor, dst=0, group=None, sync_op=True):
    """Reference: communication/send.py. Host-mediated: the payload is
    staged to host memory and delivered through the ordered (src, dst)
    channel (store on multi-process, mailbox in-process)."""
    src = get_rank()
    arr = _to_numpy(tensor)
    store = get_store()
    if store is not None and get_world_size() > 1:
        with _seq_lock:
            seq = _send_seq[(src, dst)]
            _send_seq[(src, dst)] = seq + 1
        store.set(f"p2p/{src}->{dst}/{seq}", pickle.dumps(arr))
    else:
        _mailbox.put(src, dst, arr)
    return P2PTask()


def _recv_blocking(src, dst, ticket, timeout=None, own_connection=False):
    store = get_store()
    if store is not None and get_world_size() > 1:
        if own_connection:
            # irecv runs on a background thread: the native client handle
            # is one socket whose request/response frames must not be
            # interleaved with the main thread's store traffic
            from .store import TCPStore
            store = TCPStore(store.host, store.port,
                             world_size=store.world_size)
        try:
            key = f"p2p/{src}->{dst}/{ticket}"
            raw = store.wait(key, timeout=timeout)
            store.delete_key(key)
            return pickle.loads(raw)
        finally:
            if own_connection:
                store.close()
    return _mailbox.get(src, dst, ticket, timeout=timeout)


def recv(tensor, src=0, group=None, sync_op=True, timeout=None):
    """Reference: communication/recv.py — blocks until the matching send
    lands, then copies into `tensor`."""
    dst = get_rank()
    ticket = _reserve_recv(src, dst)
    try:
        arr = _recv_blocking(src, dst, ticket, timeout=timeout)
    except TimeoutError:
        if not _unreserve_recv(src, dst, ticket):
            raise RuntimeError(
                f"recv from rank {src} timed out with later receives "
                f"outstanding — channel order cannot be restored")
        raise
    _assign(tensor, arr)
    return P2PTask()


def isend(tensor, dst=0, group=None):
    """Reference: communication/send.py isend — store delivery is already
    async on the daemon side, so the task completes immediately."""
    return send(tensor, dst, group, sync_op=False)


def irecv(tensor, src=0, group=None):
    """Reference: communication/recv.py irecv — the receive runs on a
    background thread; `task.wait()` joins it and installs the payload,
    so a posted irecv never deadlocks against the peer's own posting
    order (the NCCL-grouped semantics batch_isend_irecv relies on)."""
    dst = get_rank()
    box = [None, None]
    ticket = _reserve_recv(src, dst)  # claim order on the POSTING thread

    def work():
        try:
            box[1] = _recv_blocking(src, dst, ticket, own_connection=True)
        except BaseException as e:
            box[0] = e

    t = threading.Thread(target=work, daemon=True)
    t.start()
    return P2PTask(thread=t, result_box=box, tensor=tensor)


class P2POp:
    """Reference: communication/batch_isend_irecv.py P2POp."""

    def __init__(self, op, tensor, peer, group=None):
        if op not in (isend, irecv, send, recv):
            raise ValueError("P2POp op must be isend or irecv")
        self.op = isend if op in (isend, send) else irecv
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """Reference: communication/batch_isend_irecv.py — posts every op and
    returns the task list. Sends post first (they never block), then
    receives, mirroring the reference's grouped-launch deadlock-freedom."""
    if not p2p_op_list:
        return []
    tasks = [None] * len(p2p_op_list)
    for i, op in enumerate(p2p_op_list):
        if op.op is isend:
            tasks[i] = isend(op.tensor, op.peer, op.group)
    for i, op in enumerate(p2p_op_list):
        if op.op is irecv:
            tasks[i] = irecv(op.tensor, op.peer, op.group)
    return tasks


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Reference: communication/scatter.py — src distributes tensor_list[r]
    to rank r, received into `tensor`.

    Multi-process job: src sends each slice through the store (the gather
    pattern reversed). Single controller: every "rank" lives here, so the
    receive is tensor_list[rank] directly."""
    from . import collective as C
    if group is None:
        group = C.new_group(axis="dp")
    rank, world = get_rank(), get_world_size()
    if world > 1 and get_store() is not None:
        if rank == src:
            if tensor_list is None or len(tensor_list) != world:
                raise ValueError(
                    f"scatter src rank needs tensor_list of len {world}")
            for r in range(world):
                if r == src:
                    continue
                send(tensor_list[r], dst=r, group=group)
            chosen = tensor_list[src]
        else:
            recv(tensor, src=src, group=group)
            return tensor
    else:
        if tensor_list is None or len(tensor_list) <= rank:
            raise ValueError("scatter needs tensor_list on the src rank")
        chosen = tensor_list[rank]
    v = jnp.asarray(_to_numpy(chosen))
    if isinstance(tensor, Tensor):
        tensor._value = v
        return tensor
    return Tensor(v)


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """Reference: communication/gather.py — collect every rank's tensor on
    dst. Mesh semantics: a value sharded over the group axis contributes
    its shards; a replicated value contributes nranks identical copies."""
    from . import collective as C
    if group is None:
        group = C.new_group(axis="dp")
    if get_rank() != dst and get_world_size() > 1:
        # non-destination processes only feed the store path
        send(tensor, dst=dst, group=group)
        return
    if get_world_size() > 1:
        parts = []
        for r in range(get_world_size()):
            if r == dst:
                parts.append(Tensor(jnp.asarray(_to_numpy(tensor))))
            else:
                buf = Tensor(jnp.asarray(_to_numpy(tensor)))
                recv(buf, src=r, group=group)
                parts.append(buf)
    else:
        parts = []
        C.all_gather(parts, tensor, group=group)
    if gather_list is not None:
        gather_list.clear()
        gather_list.extend(parts)
    return parts


def reduce(tensor, dst=0, op=None, group=None, sync_op=True):
    """Reference: communication/reduce.py — all_reduce with the result
    consumed at dst. Single controller: the mesh all_reduce. Multi-process
    job (per-process local meshes): rank tensors move through the store to
    dst, which folds them."""
    from . import collective as C
    if op is None:
        op = C.ReduceOp.SUM
    if get_world_size() > 1 and get_store() is not None:
        rank, world = get_rank(), get_world_size()
        if rank != dst:
            send(tensor, dst=dst)
            return tensor
        acc = _to_numpy(tensor).copy()
        buf = Tensor(jnp.zeros_like(jnp.asarray(acc)))
        fold = {C.ReduceOp.SUM: np.add, C.ReduceOp.AVG: np.add,
                C.ReduceOp.MAX: np.maximum, C.ReduceOp.MIN: np.minimum,
                C.ReduceOp.PROD: np.multiply}.get(op)
        if fold is None:
            raise ValueError(f"unsupported ReduceOp {op} for store reduce")
        for r in range(world):
            if r == dst:
                continue
            recv(buf, src=r)
            acc = fold(acc, _to_numpy(buf))
        if op == C.ReduceOp.AVG:
            acc = acc / world
        return _assign(tensor, acc)
    return C.all_reduce(tensor, op=op, group=group)


# Per-collective call counters: every process increments on each call, so
# matched calls across ranks agree on the key and a second call can never
# read the first call's stale payload.
_obj_seq = collections.defaultdict(int)


def all_gather_object(object_list, obj, group=None):
    """Reference: communication/all_gather.py all_gather_object — python
    objects move by pickle, not device buffers."""
    world = get_world_size()
    if world == 1:
        object_list.clear()
        object_list.extend([obj])
        return
    store, rank = get_store(), get_rank()
    if store is None:
        raise RuntimeError("all_gather_object needs a launched job store")
    seq = _obj_seq["allgather"]
    _obj_seq["allgather"] += 1
    store.set(f"obj/allgather/{seq}/{rank}", pickle.dumps(obj))
    object_list.clear()
    for r in range(world):
        object_list.append(
            pickle.loads(store.wait(f"obj/allgather/{seq}/{r}")))
    # bound store memory: the LAST rank to finish reading deletes the
    # payloads (no rank can delete earlier — all must read every key)
    done = store.add(f"obj/allgather/{seq}/done", 1)
    if done == world:
        for r in range(world):
            store.delete_key(f"obj/allgather/{seq}/{r}")
        store.delete_key(f"obj/allgather/{seq}/done")


def broadcast_object_list(object_list, src=0, group=None):
    """Reference: communication/broadcast.py broadcast_object_list."""
    world = get_world_size()
    if world == 1:
        return object_list
    store, rank = get_store(), get_rank()
    if store is None:
        raise RuntimeError("broadcast_object_list needs a launched job store")
    seq = _obj_seq["bcast"]
    _obj_seq["bcast"] += 1
    if rank == src:
        store.set(f"obj/bcast/{seq}", pickle.dumps(list(object_list)))
    else:
        vals = pickle.loads(store.wait(f"obj/bcast/{seq}"))
        object_list[:] = vals
    # last reader deletes the payload (src counts itself as a reader)
    done = store.add(f"obj/bcast/{seq}/done", 1)
    if done == world:
        store.delete_key(f"obj/bcast/{seq}")
        store.delete_key(f"obj/bcast/{seq}/done")
    return object_list
