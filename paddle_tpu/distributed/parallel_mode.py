"""Reference: ParallelMode enum (fleet/base/topology.py:40)."""


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4
