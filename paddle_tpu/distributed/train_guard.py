"""Bad-step rollback + training watchdog: recovery, not just detection.

Two failure classes a long run must ride through without dying:

- **Bad steps** — a poisoned batch or numeric blow-up NaNs the loss or
  spikes the gradient norm. `TrainGuard` keeps a bounded ring of
  donation-safe engine snapshots (the engine donates its carried state to
  XLA every dispatch, so the ring holds device COPIES — engine.snapshot());
  when the tpu-san non-finite sweep or the windowed-median grad-spike
  detector fires, the run rewinds to the last good snapshot, the offending
  batch is quarantined, and the blame (first offending leaf path, batch
  id) rides a typed `BadStepError` — or, in skip mode, the step is dropped
  silently and training continues bit-identically to a run that never saw
  the batch.

- **Wedged dispatches / dead hosts** — a hung collective leaves a pod
  silently stuck. `TrainWatchdog` stamps per-host step-boundary heartbeats
  through the coordination store (`/hb/train-<host>`, server-side receipt
  ages via the existing `store.Watchdog`) and watches the engine's
  in-flight dispatch marker; a dispatch exceeding `timeout` raises a typed
  `TrainingStalledError` naming the stalled host to `on_stall` instead of
  a silent pod-wide hang.

Recovery counters (`train.recoveries`: skipped_steps / rollbacks /
preemption_saves / stalled_detections) and the `train.last_good_step`
gauge ride the obs registry — docs/observability.md.
"""
from __future__ import annotations

import threading
import time
from collections import deque

import numpy as np

from ..analysis import runtime_san as _san

__all__ = [
    "TrainGuard", "TrainWatchdog", "BadStepError", "TrainingStalledError",
    "recovery_counters",
]


class BadStepError(RuntimeError):
    """A training step produced non-finite values or a gradient-norm spike.
    Carries the forensic fields recovery tooling needs: the bad step
    number, the blamed leaf/path, the quarantined batch id, and the step
    the engine was rolled back to."""

    def __init__(self, message, *, step=None, blame=None, batch_id=None,
                 rolled_back_to=None):
        super().__init__(message)
        self.step = step
        self.blame = blame
        self.batch_id = batch_id
        self.rolled_back_to = rolled_back_to


class TrainingStalledError(RuntimeError):
    """A dispatch (or a peer host) exceeded the training watchdog timeout.
    Names the stalled host so the controller can act on it."""

    def __init__(self, message, *, host=None, phase=None, elapsed=None):
        super().__init__(message)
        self.host = host
        self.phase = phase
        self.elapsed = elapsed


# one shared per-process counter dict: TrainGuard, TrainWatchdog and
# PreemptionHandler all bump it, and it rides the obs registry under the
# ONE collector key `train.recoveries` (a plain function, held strongly)
_COUNTERS = {"skipped_steps": 0, "rollbacks": 0, "preemption_saves": 0,
             "stalled_detections": 0}
_counters_registered = False


def _collect_recoveries():
    return dict(_COUNTERS)


def recovery_counters():
    """The process-wide `train.recoveries` counter dict, registered as an
    obs collector on first use (zero overhead for runs that never touch
    the fault-tolerance layer)."""
    global _counters_registered
    if not _counters_registered:
        from ..obs.metrics import registry as _registry

        _registry().register_collector("train.recoveries",
                                       _collect_recoveries)
        _counters_registered = True
    return _COUNTERS


class TrainGuard:
    """Snapshot-ring rollback around `engine.train_batch`.

    Every `rollback_every` steps the guard captures a donation-safe
    snapshot of the engine's carried state (`engine.snapshot()` — device
    copies; the originals are donated to XLA on the next dispatch). After
    each step it checks loss/grad-norm finiteness (and catches the
    tpu-san `NonFiniteError` when the sanitizer is live) plus a windowed
    grad-norm spike detector (norm > `spike_factor` x rolling median over
    `window` good steps, armed after `min_history` of them). A bad step
    restores the most recent snapshot and quarantines the batch.

    on_bad_step:
      - "skip"  — restore + return None from step(); training continues
                  as if the batch never existed (bit-identical when the
                  snapshot is from immediately before the bad step).
      - "raise" — restore + raise the typed `BadStepError`.
    """

    def __init__(self, engine, rollback_every=1, ring_size=2, window=16,
                 spike_factor=8.0, min_history=5, on_bad_step="skip"):
        if on_bad_step not in ("skip", "raise"):
            raise ValueError("on_bad_step must be 'skip' or 'raise'")
        if rollback_every < 1:
            raise ValueError("rollback_every must be >= 1")
        self.engine = engine
        self.rollback_every = int(rollback_every)
        self.window = int(window)
        self.spike_factor = float(spike_factor)
        self.min_history = int(min_history)
        self.on_bad_step = on_bad_step
        self._ring = deque(maxlen=max(1, int(ring_size)))
        self._norms = deque(maxlen=self.window)
        self.quarantined = []        # (batch_id, blame) forensic log
        self.last_good_step = engine._step_count
        self.watchdog = None         # optional TrainWatchdog attachment
        from ..obs.metrics import registry as _registry

        recovery_counters()
        self._g_last_good = _registry().gauge(
            "train.last_good_step",
            help="newest engine step that passed the TrainGuard checks")
        self._g_last_good.set(self.last_good_step)

    # -- snapshots ---------------------------------------------------------
    def _maybe_snapshot(self):
        eng = self.engine
        if not self._ring or \
                eng._step_count - self._ring[-1][0] >= self.rollback_every:
            self._ring.append((eng._step_count, eng.snapshot()))

    def snapshot_now(self):
        """Force a ring snapshot at the current step (e.g. right after a
        checkpoint restore)."""
        self._ring.append((self.engine._step_count, self.engine.snapshot()))

    # -- the guarded step --------------------------------------------------
    def step(self, *batch, batch_id=None):
        """`engine.train_batch(*batch)` under the guard. Returns the loss
        Tensor for a good step, None for a skipped bad one (skip mode).
        The finiteness check is a deliberate host sync — the guard is the
        stability layer, and it reads one scalar per step."""
        eng = self.engine
        self._maybe_snapshot()
        blame = None
        loss_t = None
        try:
            loss_t = eng.train_batch(*batch)
            with _san.allow_host_sync("train_guard.check"):
                loss = float(loss_t._value)
                gnorm = float(eng.last_grad_norm) \
                    if eng.last_grad_norm is not None else 0.0
        except _san.NonFiniteError as e:
            blame = f"non-finite ({e})"
            gnorm = float("nan")
        if blame is None:
            if not np.isfinite(loss):
                blame = "loss is non-finite"
            elif not np.isfinite(gnorm):
                blame = "grad_norm is non-finite"
            elif len(self._norms) >= self.min_history:
                med = float(np.median(self._norms))
                if med > 0 and gnorm > self.spike_factor * med:
                    blame = (f"grad_norm spike ({gnorm:.3g} > "
                             f"{self.spike_factor:g} x median {med:.3g})")
        if blame is not None:
            return self._bad_step(blame, batch_id)
        self._norms.append(gnorm)
        self.last_good_step = eng._step_count
        self._g_last_good.set(self.last_good_step)
        if self.watchdog is not None:
            self.watchdog.beat(self.last_good_step)
        return loss_t

    def _bad_step(self, blame, batch_id):
        eng = self.engine
        bad_step = eng._step_count
        good_step, snap = self._ring[-1]
        eng.restore(snap)
        self.quarantined.append((batch_id, blame))
        c = recovery_counters()
        # a snapshot taken immediately before the bad step makes this a
        # pure skip (no good work rewound); an older one is a rollback
        rolled = good_step < bad_step - 1
        c["rollbacks" if rolled else "skipped_steps"] += 1
        err = BadStepError(
            f"bad step {bad_step}: {blame} — batch {batch_id!r} "
            f"quarantined, engine rolled back to step {good_step}",
            step=bad_step, blame=blame, batch_id=batch_id,
            rolled_back_to=good_step)
        if self.on_bad_step == "raise":
            raise err
        return None


class TrainWatchdog:
    """Step-boundary heartbeats + wedged-dispatch detection.

    - `beat(step)` stamps `/hb/train-<host>` in the coordination store at
      each step boundary; the existing `store.Watchdog` then reports any
      host whose stamp goes stale (`peer_ttl`) — a host wedged inside a
      dispatch stops beating and is named by its peers.
    - a background thread watches the engine's in-flight dispatch marker
      (`engine._inflight`, set around every compiled-step dispatch); a
      dispatch older than `timeout` raises `TrainingStalledError` into
      `on_stall` (default: record on `self.stalled` for the training loop
      to collect via `raise_if_stalled()` at the next step boundary —
      which a truly wedged dispatch never reaches, hence `on_stall` for
      processes that must exit and let the elastic relaunch take over).
    """

    def __init__(self, engine=None, timeout=30.0, interval=None, store=None,
                 host=None, on_stall=None, peer_ttl=None):
        self.engine = engine
        self.timeout = float(timeout)
        self.interval = float(interval) if interval is not None \
            else max(0.05, min(1.0, self.timeout / 4))
        self.store = store
        if host is None:
            from .env import get_rank
            host = f"rank{get_rank()}"
        self.host = str(host)
        self.on_stall = on_stall
        self.peer_ttl = float(peer_ttl) if peer_ttl is not None \
            else self.timeout
        self.stalled = None
        # dead-peer blame fires once per (host, rejoin-epoch): a host
        # that rejoins after elastic relaunch bumps its epoch (via the
        # store watchdog's revival callback) so a SECOND wedge of the
        # same name is still reported — without the epoch the rejoined
        # host would inherit the spent count and wedge silently
        self._blamed = set()
        self._host_epoch = {}
        self._stop = threading.Event()
        self._thread = None
        self._peer_dog = None
        if store is not None:
            from .store import Watchdog

            self._peer_dog = Watchdog(store, ttl=self.peer_ttl,
                                      interval=self.interval,
                                      on_failure=self._peers_dead,
                                      on_recovery=self._peers_recovered)

    # -- heartbeats --------------------------------------------------------
    def _hb_key(self):
        return f"/hb/train-{self.host}"

    def beat(self, step=None):
        """Stamp this host's step-boundary heartbeat (server-side receipt
        age is what peers watch — the value is informational)."""
        if self.store is not None:
            self.store.set(self._hb_key(), str(-1 if step is None else step))

    # -- detection ---------------------------------------------------------
    def _train_peers(self, names):
        return [n[len("train-"):] for n in names
                if n.startswith("train-") and n != f"train-{self.host}"]

    def _peers_dead(self, names):
        for peer in self._train_peers(names):
            self._stall(TrainingStalledError(
                f"training host {peer!r} stopped heartbeating "
                f"(> {self.peer_ttl:g}s since its last step boundary)",
                host=peer, phase="heartbeat", elapsed=self.peer_ttl))

    def _peers_recovered(self, names):
        """A dead peer is heartbeating again (elastic relaunch under the
        same name): re-arm its blame by bumping the per-host epoch, and
        drop a pending stall that blamed it — the next wedge of that
        host must be reported as a FRESH event, not swallowed by the
        spent count."""
        for peer in self._train_peers(names):
            self._host_epoch[peer] = self._host_epoch.get(peer, 0) + 1
            if self.stalled is not None and self.stalled.host == peer:
                self.stalled = None

    def check(self):
        """One local sweep of the engine's in-flight dispatch marker."""
        eng = self.engine
        inflight = getattr(eng, "_inflight", None) if eng is not None \
            else None
        if inflight is not None:
            site, t0 = inflight
            elapsed = time.monotonic() - t0
            if elapsed > self.timeout:
                self._stall(TrainingStalledError(
                    f"dispatch {site!r} on host {self.host!r} has been "
                    f"in flight {elapsed:.1f}s (> watchdog timeout "
                    f"{self.timeout:g}s) — wedged collective or device "
                    f"hang", host=self.host, phase=site, elapsed=elapsed))
                return True
        return False

    def _stall(self, err):
        key = (err.host, self._host_epoch.get(err.host, 0))
        if key in self._blamed:
            return  # one error per (host, rejoin-epoch) of blame
        self._blamed.add(key)
        # blame upgrade: a wedge with a PENDING collective-schedule
        # mismatch (PADDLE_TPU_COMMCHECK=1) is not "stalled" — it is a
        # divergent cohort waiting in a collective that will never
        # complete; report the divergent host + first divergent
        # collective instead of the generic timeout
        try:
            from ..analysis import commcheck as _cc

            if _cc.enabled():
                mm = _cc.pending_mismatch()
                if mm is not None:
                    err = mm
        except Exception:  # tpu-lint: disable=TL007 — the upgrade is
            pass           # best-effort; the stall must still surface
        recovery_counters()["stalled_detections"] += 1
        if self.stalled is None:
            self.stalled = err
        if self.on_stall is not None:
            self.on_stall(err)

    def raise_if_stalled(self):
        """Surface a recorded stall at a step boundary (peer-death case —
        the local loop is still running)."""
        if self.stalled is not None:
            raise self.stalled

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        if self._peer_dog is not None:
            self._peer_dog.start()

        def loop():
            while not self._stop.wait(self.interval):
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="paddle-tpu-train-watchdog")
        self._thread.start()
        return self

    def stop(self):
        """Stop the threads and retire this host's heartbeat key so a
        clean shutdown leaks nothing into the store."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._peer_dog is not None:
            self._peer_dog.stop()
        if self.store is not None:
            try:
                self.store.delete_key(self._hb_key())
            except Exception:  # tpu-lint: disable=TL007 — teardown path
                pass
