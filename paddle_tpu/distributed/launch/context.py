"""Launch context: CLI args + environment (reference:
launch/context/__init__.py Context and args parsing in main.py)."""
from __future__ import annotations

import argparse
import os
import socket


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a (multi-process) paddle_tpu job")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", "1")),
                   help="number of nodes (hosts) in the job")
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.environ.get("PADDLE_NPROC_PER_NODE", "1")),
                   help="processes per node (TPU: one controller per host)")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="host:port of the rendezvous store "
                        "(auto-hosted locally when omitted)")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "-1")),
                   help="node rank; -1 = assign via the store")
    p.add_argument("--job_id", default=os.environ.get("PADDLE_JOB_ID",
                                                      "default"))
    p.add_argument("--log_dir", default=os.environ.get("PADDLE_LOG_DIR"),
                   help="write per-rank logs under this dir")
    p.add_argument("--elastic", action="store_true",
                   help="relaunch failed workers (elastic mode)")
    p.add_argument("--ckpt_dir", default=os.environ.get("PADDLE_CKPT_DIR"),
                   help="fault-tolerant checkpoint root: exported to "
                        "workers as PADDLE_TPU_CKPT_DIR (consumed by "
                        "hapi ModelCheckpoint auto-resume / "
                        "CheckpointManager); on elastic relaunch the "
                        "controller sweeps torn checkpoints left by the "
                        "crash before respawning")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="elastic: maximum relaunch attempts")
    p.add_argument("--mesh", default=os.environ.get("PADDLE_MESH"),
                   help="declarative mesh for the whole job, e.g. "
                        "'dp=2,fsdp=4' or 'fsdp=8,dcn_dp=2': exported to "
                        "every worker as PADDLE_TPU_MESH so each host of "
                        "the rendezvous builds the IDENTICAL hybrid "
                        "ICI*DCN mesh (consumed by init_parallel_env; "
                        "MeshConfig(fsdp=N) selects fsdp-by-default "
                        "training, docs/sharding.md)")
    p.add_argument("--devices", default=os.environ.get("PADDLE_DEVICES"),
                   help="visible device ids for this node (comma-separated)")
    p.add_argument("-m", "--module", action="store_true",
                   help="treat training_script as a module name "
                        "(python -m semantics)")
    p.add_argument("training_script",
                   help="the script (or, with -m, module name) to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def free_port():
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Context:
    def __init__(self, args):
        self.args = args
        self.node_ip = os.environ.get("POD_IP", "127.0.0.1")
        self.world_size = args.nnodes * args.nproc_per_node
