"""paddle_tpu.distributed.launch — multi-process job launcher.

Reference analog: `python -m paddle.distributed.launch` (launch/main.py:20)
with its Context -> Controller pipeline (controllers/collective.py:272
spawning per-rank processes), `HTTPMaster`/`ETCDMaster` rendezvous
(controllers/master.py:73/186), the log watcher (watcher.py), and elastic
relaunch (fleet/elastic/manager.py:126).

TPU-native redesign: one controller process per HOST drives all local
chips through PJRT, so `--nproc_per_node` defaults to 1 (the reference
spawns one proc per GPU). Rendezvous rides the native coordination store
(rank0-hosted TCPStore): node ranks come from an atomic counter, the
world address list is published as KV entries, and liveness is heartbeat
keys that an elastic controller watches to trigger relaunch.
"""
from .context import Context, parse_args  # noqa: F401
from .controller import Controller, main  # noqa: F401
