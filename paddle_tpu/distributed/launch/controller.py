"""Launch controller: rendezvous, process spawn, log watch, elastic loop.

Reference analog: controllers/collective.py (CollectiveController.build_pod
+ _get_entrypoint spawning per-rank procs with PADDLE_TRAINER_* env),
controllers/master.py rendezvous, watcher.py log aggregation, and
fleet/elastic/manager.py's relaunch-on-failure loop.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

from .context import Context, free_port


class Proc:
    def __init__(self, rank, popen, log_path=None):
        self.rank = rank
        self.popen = popen
        self.log_path = log_path


class Controller:
    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.args = ctx.args
        self.procs: list[Proc] = []
        self._store = None
        self._shutdown = threading.Event()

    # -- rendezvous --------------------------------------------------------
    @staticmethod
    def _is_local_host(host):
        import socket

        if host in ("", "localhost", "127.0.0.1", "0.0.0.0"):
            return True
        try:
            addrs = {ai[4][0] for ai in socket.getaddrinfo(host, None)}
        except socket.gaierror:
            return False
        local = {"127.0.0.1", "::1"}
        try:
            local |= {ai[4][0] for ai in socket.getaddrinfo(
                socket.gethostname(), None)}
        except socket.gaierror:
            pass
        return bool(addrs & local)

    def rendezvous(self):
        """Determine (node_rank, master addr); the controller on the master
        host also hosts the store daemon.

        Single-node default: host a store on a free port locally.
        Multi-node: --master required; explicitly ranked nodes claim their
        rank, auto-rank (-1) nodes draw from an atomic counter skipping
        claimed ranks (reference: master.py sync_peers)."""
        from ..store import TCPStore

        args = self.args
        if args.master is None:
            if args.nnodes != 1:
                raise SystemExit("--master host:port is required for "
                                 "multi-node jobs")
            port = free_port()
            self.master = f"127.0.0.1:{port}"
            self._store = TCPStore("127.0.0.1", port, is_master=True,
                                   world_size=args.nnodes)
            self.node_rank = 0
            return
        host, _, port = args.master.rpartition(":")
        port = int(port)
        self.master = args.master
        # the node running on the master address hosts the daemon (works
        # with auto-rank too); everyone else is a client
        if args.rank == 0 or (args.rank == -1 and self._is_local_host(host)):
            try:
                self._store = TCPStore(host, port, is_master=True,
                                       world_size=args.nnodes)
            except RuntimeError:
                # lost the local bind race to a peer controller
                self._store = TCPStore(host, port, world_size=args.nnodes)
        else:
            self._store = TCPStore(host, port, world_size=args.nnodes)
        job = args.job_id
        # claims are atomic: the first add() on a rank's claim key wins,
        # so explicit and auto assignment cannot race into the same rank.
        # A restarted node may RE-claim its explicit rank when the previous
        # holder's controller heartbeat has gone stale (elastic rejoin).
        if args.rank >= 0:
            gen = self._store.add(f"/rdzv/{job}/claim/{args.rank}", 1)
            if gen != 1:
                # conflict. Give the current holder a grace window to prove
                # liveness (its heartbeat starts right after its claim);
                # then only the LATEST claimant (per the atomic counter)
                # may take over — so concurrent rejoiners can't both win.
                ttl = float(os.environ.get("PADDLE_RDZV_TTL", "5"))
                deadline = time.monotonic() + ttl
                while time.monotonic() < deadline:
                    age = self._store.heartbeat_age(f"ctl/{job}/{args.rank}")
                    if age is not None and age < ttl:
                        raise SystemExit(
                            f"node rank {args.rank} already claimed by a "
                            "live node")
                    time.sleep(min(0.25, ttl / 4))
                cur = self._store.get_nowait(f"/rdzv/{job}/claim/{args.rank}")
                if cur is not None and int(cur) != gen:
                    raise SystemExit(
                        f"node rank {args.rank} superseded by a newer "
                        "claimant")
            self.node_rank = args.rank
        else:
            while True:
                n = self._store.add(f"/rdzv/{job}/next", 1) - 1
                if self._store.add(f"/rdzv/{job}/claim/{n}", 1) == 1:
                    self.node_rank = n
                    break
        # liveness lease backing the re-claim rule above; beat well inside
        # the TTL so a live holder is never mistaken for stale by a
        # rejoiner sampling with the same TTL
        ttl = float(os.environ.get("PADDLE_RDZV_TTL", "5"))
        self._store.start_heartbeat(f"ctl/{job}/{self.node_rank}",
                                    interval=min(1.0, ttl / 4))

    # -- spawn -------------------------------------------------------------
    def _env_for(self, local_rank, restart_epoch=0):
        args = self.args
        world = args.nnodes * args.nproc_per_node
        rank = self.node_rank * args.nproc_per_node + local_rank
        env = dict(os.environ)
        env.update({
            # framework env (consumed by init_parallel_env, env.py)
            "PADDLE_TPU_MASTER": self.master,
            "PADDLE_TPU_PROCESS_ID": str(rank),
            "PADDLE_TPU_NUM_PROCESSES": str(world),
            # reference-parity env (PADDLE_TRAINER_*, parallel.py:943)
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_RESTART_EPOCH": str(restart_epoch),
            "PADDLE_JOB_ID": args.job_id,
        })
        if getattr(args, "ckpt_dir", None):
            env["PADDLE_TPU_CKPT_DIR"] = args.ckpt_dir
        if getattr(args, "mesh", None):
            # canonical serialized MeshConfig: parse-validate HERE so a
            # bad --mesh fails the launch on the controller, not worker N
            # mid-rendezvous; the SAME payload survives elastic relaunches
            # (spawn() re-runs this), so a restarted world rebuilds the
            # identical mesh and auto-resume proceeds unchanged
            from ...sharding import MeshConfig

            env["PADDLE_TPU_MESH"] = MeshConfig.parse(args.mesh).to_env()
        if world > 1:
            # jax.distributed coordinator (data plane) on master host,
            # distinct port from the KV store
            mhost, _, mport = self.master.rpartition(":")
            env["PADDLE_TPU_COORDINATOR"] = \
                f"{mhost}:{int(mport) + 1}"
        if args.devices:
            env["CUDA_VISIBLE_DEVICES"] = args.devices
            env["TPU_VISIBLE_DEVICES"] = args.devices
        return env

    def spawn(self, restart_epoch=0):
        args = self.args
        self.procs = []
        if args.log_dir:
            os.makedirs(args.log_dir, exist_ok=True)
        for lr in range(args.nproc_per_node):
            if getattr(args, "module", False):
                cmd = [sys.executable, "-m", args.training_script,
                       *args.training_script_args]
            else:
                cmd = [sys.executable, args.training_script,
                       *args.training_script_args]
            log_path = None
            stdout = stderr = None
            f = None
            if args.log_dir:
                rank = self.node_rank * args.nproc_per_node + lr
                log_path = os.path.join(args.log_dir,
                                        f"worker.{rank}.log")
                f = open(log_path, "ab")
                stdout, stderr = f, subprocess.STDOUT
            p = subprocess.Popen(cmd, env=self._env_for(lr, restart_epoch),
                                 stdout=stdout, stderr=stderr)
            if f is not None:
                f.close()  # Popen dup'd the fd; don't leak per relaunch
            self.procs.append(Proc(lr, p, log_path))

    def terminate(self, sig=signal.SIGTERM, grace=10.0):
        for pr in self.procs:
            if pr.popen.poll() is None:
                try:
                    pr.popen.send_signal(sig)
                except ProcessLookupError:
                    pass
        deadline = time.monotonic() + grace
        for pr in self.procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                pr.popen.wait(timeout=left)
            except subprocess.TimeoutExpired:
                pr.popen.kill()

    # -- supervision -------------------------------------------------------
    def watch(self):
        """Block until all workers exit; fail fast on the first nonzero
        exit (reference: watcher/pod watch loop). Returns exit code."""
        while True:
            alive = 0
            for pr in self.procs:
                rc = pr.popen.poll()
                if rc is None:
                    alive += 1
                elif rc != 0:
                    from ..preemption import is_clean_preempt

                    if is_clean_preempt(rc):
                        print(f"worker rank {pr.rank} exited on clean "
                              f"preemption (code {rc})", file=sys.stderr)
                    else:
                        print(f"worker rank {pr.rank} failed with code {rc}",
                              file=sys.stderr)
                    self.terminate()
                    return rc
            if alive == 0:
                return 0
            time.sleep(0.2)

    def run(self):
        from ..preemption import is_clean_preempt

        self.rendezvous()
        args = self.args
        restarts = 0   # FAILURE relaunches — the budget args.max_restarts caps
        spawns = 0     # all incarnations, incl. free clean-preempt relaunches
        while True:
            self.spawn(restart_epoch=spawns)
            spawns += 1
            rc = self.watch()
            if rc == 0:
                return 0
            if not args.elastic:
                return rc
            preempted = is_clean_preempt(rc)
            if preempted:
                # the worker checkpointed inside its grace window and
                # exited PREEMPT_EXIT_CODE on purpose — relaunching costs
                # nothing from the retry budget (a preemption storm must
                # not exhaust the failure allowance)
                print("elastic: clean preemption (workers checkpointed "
                      "and exited within the grace window); relaunching "
                      f"without spending a retry "
                      f"({restarts}/{args.max_restarts} used)",
                      file=sys.stderr)
            elif restarts >= args.max_restarts:
                return rc
            else:
                restarts += 1
            # all workers are dead here (watch() tears down on first
            # failure), so sweeping torn checkpoints is race-free; the
            # relaunched workers then auto-resume from the newest
            # COMMITTED checkpoint (fleet/elastic resume path)
            if getattr(args, "ckpt_dir", None):
                from ..checkpoint.manager import clean_uncommitted

                try:
                    removed = clean_uncommitted(args.ckpt_dir)
                except OSError as e:
                    print(f"elastic: checkpoint sweep failed: {e}",
                          file=sys.stderr)
                else:
                    if removed:
                        print("elastic: swept torn checkpoints "
                              f"{sorted(removed)}", file=sys.stderr)
            if not preempted:
                print(f"elastic: relaunching workers after failure "
                      f"(attempt {restarts}/{args.max_restarts})",
                      file=sys.stderr)

    def close(self):
        if self._store is not None:
            self._store.close()
            self._store = None


def main(argv=None):
    from .context import parse_args

    args = parse_args(argv)
    ctl = Controller(Context(args))
    try:
        return ctl.run()
    except KeyboardInterrupt:
        ctl.terminate()
        return 130
    finally:
        ctl.close()
