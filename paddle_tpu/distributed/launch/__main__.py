import sys

from .controller import main

sys.exit(main())
