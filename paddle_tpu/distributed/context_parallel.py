"""Context parallelism: ring attention + Ulysses (all-to-all) attention.

The reference has NO ring attention / Ulysses (SURVEY.md §5 "long-context":
it only offers Megatron-style sequence parallel around TP blocks,
fleet/utils/sequence_parallel_utils.py:230, and the `sep` hybrid-topology
axis with model-level sequence splitting, fleet/base/topology.py:64,184).
This module is the TPU-native long-context answer that *exceeds* the
reference: sequence shards live on the `sep` mesh axis and

- **ring attention** streams K/V blocks around the ICI ring with
  `jax.lax.ppermute`, combining per-block partial attention with the
  online-softmax (flash) recurrence, so peak memory is O(S_local) and the
  ppermute overlaps with the block matmuls;
- **Ulysses attention** trades sequence sharding for head sharding with two
  `all_to_all`s, running dense flash attention on full sequences per head
  group.

Both run inside `jax.shard_map` regions nested in the engine's single jitted
train step, composing with dp/sharding batch split and mp head split.
"""
from __future__ import annotations

import contextlib
import math
import threading
from functools import partial

import jax
import jax.numpy as jnp

from .. import sharding as _shardlib

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "context_parallel_attention",
    "context_parallel_guard",
    "active_context_parallel",
]


# ---------------------------------------------------------------------------
# Local (inside-shard_map) bodies. q/k/v: [batch, seq_local, heads, head_dim].
# ---------------------------------------------------------------------------


def _ring_attention_local(q, k, v, *, axis_name, causal, scale):
    """Flash-style streaming attention over K/V blocks rotating on the ring.

    Device p starts with its own K/V block; after t rotations it holds the
    block originally owned by (p - t) mod n. Per block: masked scores →
    online-softmax update of (o, m, l); K/V then hop one step around the
    `axis_name` ring (ppermute — XLA maps this onto neighbouring ICI links).
    """
    n = jax.lax.psum(1, axis_name)
    p = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # [b,h,sq,d]
    q_pos = p * s_loc + jnp.arange(s_loc)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_update(acc, k_blk, v_blk, src):
        o, m, l = acc
        kf = k_blk.astype(jnp.float32).transpose(0, 2, 1, 3)
        vf = v_blk.astype(jnp.float32).transpose(0, 2, 1, 3)
        s_ = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            s_ = jnp.where(mask, s_, -1e30)
        m_new = jnp.maximum(m, s_.max(-1))
        p_ = jnp.exp(s_ - m_new[..., None])
        if causal:
            p_ = p_ * mask  # robust when a whole row is masked (m_new=-1e30)
        corr = jnp.exp(m - m_new)
        l = l * corr + p_.sum(-1)
        o = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p_, vf)
        return o, m_new, l

    def body(t, carry):
        acc, k_blk, v_blk = carry
        # send the current block onward BEFORE consuming it: the ppermute
        # has no data dependency on the block matmuls, so XLA can overlap
        # the ICI hop with compute; n-1 hops total (the last arrival is
        # consumed after the loop)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        acc = block_update(acc, k_blk, v_blk, (p - t) % n)
        return acc, k_nxt, v_nxt

    acc = (jnp.zeros((b, h, s_loc, d), jnp.float32),
           jnp.full((b, h, s_loc), -1e30, jnp.float32),
           jnp.zeros((b, h, s_loc), jnp.float32))
    acc, k_last, v_last = jax.lax.fori_loop(0, n - 1, body, (acc, k, v))
    o, m, l = block_update(acc, k_last, v_last, (p - (n - 1)) % n)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def _ulysses_attention_local(q, k, v, *, axis_name, causal, scale):
    """All-to-all head/sequence exchange: [b, S/n, h, d] -> [b, S, h/n, d],
    dense flash attention on the full sequence per head group, then the
    inverse exchange. One all_to_all pair per tensor — O(S·h·d/n) bytes on
    ICI, independent of S² (the attention itself never crosses chips)."""
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    q = a2a(q, split_axis=2, concat_axis=1)
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    out = jax.nn.dot_product_attention(q, k, v, is_causal=causal, scale=scale)
    return a2a(out, split_axis=1, concat_axis=2)


# ---------------------------------------------------------------------------
# shard_map wrappers.
# ---------------------------------------------------------------------------


def _cp_spec(mesh, seq_axis, batch_axes, head_axis):
    batch = tuple(a for a in batch_axes if a in mesh.shape and mesh.shape[a] > 1)
    head = head_axis if (head_axis in mesh.shape and mesh.shape[head_axis] > 1) else None
    return _shardlib.spec(batch if batch else None, seq_axis, head, None)


def context_parallel_attention(q, k, v, mesh, *, mode="ring", seq_axis="sep",
                               causal=True, scale=None,
                               batch_axes=("dp", "sharding"), head_axis="mp"):
    """Sequence-sharded self-attention over `seq_axis` of `mesh`.

    q/k/v: [batch, seq, heads, head_dim] global arrays (or tracers inside a
    jit using `mesh`); seq must divide by mesh.shape[seq_axis]; with
    mode="ulysses", local heads must also divide by it.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mode == "ring":
        body = partial(_ring_attention_local, axis_name=seq_axis,
                       causal=causal, scale=scale)
    elif mode == "ulysses":
        body = partial(_ulysses_attention_local, axis_name=seq_axis,
                       causal=causal, scale=scale)
    else:
        raise ValueError(f"unknown context-parallel mode {mode!r}")
    spec = _cp_spec(mesh, seq_axis, batch_axes, head_axis)
    from ..compat import shard_map
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ring_attention(q, k, v, mesh, *, seq_axis="sep", causal=True, scale=None,
                   batch_axes=("dp", "sharding"), head_axis="mp"):
    """Ring attention (ppermute K/V rotation + online softmax)."""
    return context_parallel_attention(
        q, k, v, mesh, mode="ring", seq_axis=seq_axis, causal=causal,
        scale=scale, batch_axes=batch_axes, head_axis=head_axis)


def ulysses_attention(q, k, v, mesh, *, seq_axis="sep", causal=True,
                      scale=None, batch_axes=("dp", "sharding"),
                      head_axis="mp"):
    """Ulysses all-to-all sequence/head-parallel attention."""
    return context_parallel_attention(
        q, k, v, mesh, mode="ulysses", seq_axis=seq_axis, causal=causal,
        scale=scale, batch_axes=batch_axes, head_axis=head_axis)


# ---------------------------------------------------------------------------
# Trace-time routing state: the engine enables this around its traced loss so
# model-level `F.scaled_dot_product_attention` calls transparently become
# context-parallel when the mesh has a sep axis > 1.
# ---------------------------------------------------------------------------


class _CPState(threading.local):
    def __init__(self):
        self.config = None  # (mesh, mode, seq_axis)


_cp_state = _CPState()


def active_context_parallel():
    """(mesh, mode, seq_axis) if a context_parallel_guard is active."""
    return _cp_state.config


@contextlib.contextmanager
def context_parallel_guard(mesh, mode="ring", seq_axis="sep"):
    prev = _cp_state.config
    _cp_state.config = (mesh, mode, seq_axis)
    try:
        yield
    finally:
        _cp_state.config = prev
