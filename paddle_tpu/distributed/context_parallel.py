"""Context parallelism: ring attention + Ulysses (all-to-all) attention.

The reference has NO ring attention / Ulysses (SURVEY.md §5 "long-context":
it only offers Megatron-style sequence parallel around TP blocks,
fleet/utils/sequence_parallel_utils.py:230, and the `sep` hybrid-topology
axis with model-level sequence splitting, fleet/base/topology.py:64,184).
This module is the TPU-native long-context answer that *exceeds* the
reference: sequence shards live on the context-parallel mesh axis ("cp" on
MeshConfig meshes, "sep" on the legacy hybrid topology) and

- **ring attention** streams K/V blocks around the ICI ring with
  `jax.lax.ppermute`, combining per-block partial attention with the
  online-softmax (flash) recurrence, so peak memory is O(S_local) and the
  ppermute overlaps with the block matmuls. Two interchangeable step
  implementations: an einsum body (any shape, CPU-friendly) and the Pallas
  flash fwd/bwd kernels (`ops/pallas/flash_attention.flash_fwd_pos` /
  `flash_bwd_pos`) composed under one custom_vjp (`impl="flash"`);
- **Ulysses attention** trades sequence sharding for head sharding with two
  `all_to_all`s, running dense flash attention on full sequences per head
  group.

Causal load balancing: with naive contiguous placement, ring step t is all
useful work for late shards and all masked work for early ones. The zigzag
placement (Ring Attention / llama3 recipe) gives device p the global
chunks (p, 2n-1-p) — each device owns an early AND a late chunk, so every
ring step carries ~the same number of unmasked (query, key) pairs. The
permutation is applied to the GLOBAL arrays outside the shard_map (a
static gather the surrounding jit fuses into the sharding transfer) and
masking runs off explicit per-row global positions that rotate around the
ring alongside K/V.

Both run inside `jax.shard_map` regions nested in the engine's single jitted
train step, composing with dp/fsdp batch split and tp/mp head split.
"""
from __future__ import annotations

import contextlib
import math
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import sharding as _shardlib

__all__ = [
    "ring_attention",
    "ulysses_attention",
    "context_parallel_attention",
    "context_parallel_guard",
    "active_context_parallel",
]


# ---------------------------------------------------------------------------
# Placement helpers.
# ---------------------------------------------------------------------------


def zigzag_permutation(seq_len, n_shards):
    """Global row permutation for load-balanced causal placement: shard p
    receives chunks (p, 2n-1-p) of size seq_len/(2n). Returns (perm,
    inverse) index arrays; `x[:, perm]` places rows, `y[:, inverse]`
    restores the natural order."""
    if seq_len % (2 * n_shards):
        raise ValueError(f"zigzag placement needs seq_len divisible by "
                         f"2*n_shards, got {seq_len} / {n_shards}")
    c = seq_len // (2 * n_shards)
    perm = np.concatenate([
        np.concatenate([np.arange(p * c, (p + 1) * c),
                        np.arange((2 * n_shards - 1 - p) * c,
                                  (2 * n_shards - p) * c)])
        for p in range(n_shards)])
    return perm, np.argsort(perm)


def _local_positions(axis_name, s_loc, balanced):
    """Global positions of this shard's rows (int32 [s_loc]), matching
    `zigzag_permutation` when balanced else contiguous placement."""
    p = jax.lax.axis_index(axis_name)
    n = jax.lax.psum(1, axis_name)
    if balanced:
        c = s_loc // 2
        lo = p * c + jnp.arange(c, dtype=jnp.int32)
        hi = (2 * n - 1 - p) * c + jnp.arange(c, dtype=jnp.int32)
        return jnp.concatenate([lo, hi])
    return p * s_loc + jnp.arange(s_loc, dtype=jnp.int32)


def _to_bh(x):
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _from_bh(x, b, h):
    bh, s, d = x.shape
    return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Local (inside-shard_map) bodies. q/k/v: [batch, seq_local, heads, head_dim].
# ---------------------------------------------------------------------------


def _ring_attention_local(q, k, v, *, axis_name, causal, scale, balanced):
    """Flash-style streaming attention over K/V blocks rotating on the ring.

    Device p starts with its own K/V block; after t rotations it holds the
    block originally owned by (p - t) mod n. Per block: masked scores →
    online-softmax update of (o, m, l); K/V (and their global position
    vector) then hop one step around the `axis_name` ring (ppermute — XLA
    maps this onto neighbouring ICI links).
    """
    n = jax.lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    qf = (q.astype(jnp.float32) * scale).transpose(0, 2, 1, 3)  # [b,h,sq,d]
    q_pos = _local_positions(axis_name, s_loc, balanced)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_update(acc, k_blk, v_blk, k_pos):
        o, m, l = acc
        kf = k_blk.astype(jnp.float32).transpose(0, 2, 1, 3)
        vf = v_blk.astype(jnp.float32).transpose(0, 2, 1, 3)
        s_ = jnp.einsum("bhqd,bhkd->bhqk", qf, kf)
        if causal:
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            s_ = jnp.where(mask, s_, -1e30)
        m_new = jnp.maximum(m, s_.max(-1))
        p_ = jnp.exp(s_ - m_new[..., None])
        if causal:
            p_ = p_ * mask  # robust when a whole row is masked (m_new=-1e30)
        corr = jnp.exp(m - m_new)
        l = l * corr + p_.sum(-1)
        o = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p_, vf)
        return o, m_new, l

    def body(t, carry):
        acc, k_blk, v_blk, kp = carry
        # send the current block onward BEFORE consuming it: the ppermute
        # has no data dependency on the block matmuls, so XLA can overlap
        # the ICI hop with compute; n-1 hops total (the last arrival is
        # consumed after the loop)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        kp_nxt = jax.lax.ppermute(kp, axis_name, perm)
        acc = block_update(acc, k_blk, v_blk, kp)
        return acc, k_nxt, v_nxt, kp_nxt

    acc = (jnp.zeros((b, h, s_loc, d), jnp.float32),
           jnp.full((b, h, s_loc), -1e30, jnp.float32),
           jnp.zeros((b, h, s_loc), jnp.float32))
    acc, k_last, v_last, kp_last = jax.lax.fori_loop(
        0, n - 1, body, (acc, k, v, q_pos))
    o, m, l = block_update(acc, k_last, v_last, kp_last)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


# -- ring steps through the Pallas flash kernels (one custom_vjp) -----------


def _merge_partial(out, lse, o_blk, lse_blk):
    """Online-softmax merge of one ring step's normalized partial: a
    fully-masked partial arrives as (0, ~-inf) and gets weight 0."""
    lse_new = jnp.logaddexp(lse, lse_blk)
    out = out * jnp.exp(lse - lse_new) \
        + o_blk.astype(jnp.float32) * jnp.exp(lse_blk - lse_new)
    return out, lse_new


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale, balanced,
                         interpret):
    from ..ops.pallas.flash_attention import flash_fwd_pos

    n = jax.lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    q_pos = _local_positions(axis_name, s_loc, balanced)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qb, kb, vb = _to_bh(q), _to_bh(k), _to_bh(v)

    def body(t, carry):
        out, lse, k_c, v_c, kp_c = carry
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        kp_n = jax.lax.ppermute(kp_c, axis_name, perm)
        o_blk, lse_blk = flash_fwd_pos(
            qb, k_c, v_c, q_pos, kp_c, scale=scale, causal=causal,
            interpret=interpret)
        out, lse = _merge_partial(out, lse, o_blk, lse_blk)
        return out, lse, k_n, v_n, kp_n

    init = (jnp.zeros(qb.shape, jnp.float32),
            jnp.full((b * h, s_loc, 1), -1e30, jnp.float32), kb, vb, q_pos)
    out, lse, k_l, v_l, kp_l = jax.lax.fori_loop(0, n - 1, body, init)
    o_blk, lse_blk = flash_fwd_pos(
        qb, k_l, v_l, q_pos, kp_l, scale=scale, causal=causal,
        interpret=interpret)
    out, lse = _merge_partial(out, lse, o_blk, lse_blk)
    return out.astype(q.dtype), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash_local(q, k, v, axis_name, causal, scale, balanced,
                      interpret):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                  balanced, interpret)
    b, s_loc, h, d = q.shape
    return _from_bh(out, b, h)


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, scale, balanced,
                         interpret):
    out_bh, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale,
                                       balanced, interpret)
    b, s_loc, h, d = q.shape
    return _from_bh(out_bh, b, h), (q, k, v, out_bh, lse)


def _ring_flash_bwd_rule(axis_name, causal, scale, balanced, interpret,
                         res, do):
    """Ring backward: dq accumulates at home; (k, v, dk, dv) rotate
    TOGETHER for n full hops, each visited device adding its q-shard's
    contribution — after n rotations the accumulated dk/dv are home. The
    FA-2 identity (p from the GLOBAL merged lse, ds = p*(dp - delta))
    makes every step independently computable from global statistics."""
    from ..ops.pallas.flash_attention import flash_bwd_pos

    q, k, v, out_bh, lse = res
    n = jax.lax.psum(1, axis_name)
    b, s_loc, h, d = q.shape
    q_pos = _local_positions(axis_name, s_loc, balanced)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qb, kb, vb, dob = _to_bh(q), _to_bh(k), _to_bh(v), _to_bh(do)
    delta = jnp.sum(dob.astype(jnp.float32) * out_bh.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def body(t, carry):
        dq, k_c, v_c, kp_c, dk_c, dv_c = carry
        dq_i, dk_i, dv_i = flash_bwd_pos(
            qb, k_c, v_c, dob, lse, delta, q_pos, kp_c, scale=scale,
            causal=causal, interpret=interpret)
        dq = dq + dq_i.astype(jnp.float32)
        dk_c = dk_c + dk_i.astype(jnp.float32)
        dv_c = dv_c + dv_i.astype(jnp.float32)
        return (dq,
                jax.lax.ppermute(k_c, axis_name, perm),
                jax.lax.ppermute(v_c, axis_name, perm),
                jax.lax.ppermute(kp_c, axis_name, perm),
                jax.lax.ppermute(dk_c, axis_name, perm),
                jax.lax.ppermute(dv_c, axis_name, perm))

    init = (jnp.zeros(qb.shape, jnp.float32), kb, vb, q_pos,
            jnp.zeros(kb.shape, jnp.float32),
            jnp.zeros(vb.shape, jnp.float32))
    dq, _, _, _, dk, dv = jax.lax.fori_loop(0, n, body, init)
    return (_from_bh(dq.astype(q.dtype), b, h),
            _from_bh(dk.astype(k.dtype), b, h),
            _from_bh(dv.astype(v.dtype), b, h))


_ring_flash_local.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def _ulysses_attention_local(q, k, v, *, axis_name, causal, scale):
    """All-to-all head/sequence exchange: [b, S/n, h, d] -> [b, S, h/n, d],
    dense flash attention on the full sequence per head group, then the
    inverse exchange. One all_to_all pair per tensor — O(S·h·d/n) bytes on
    ICI, independent of S² (the attention itself never crosses chips)."""
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    q = a2a(q, split_axis=2, concat_axis=1)
    k = a2a(k, split_axis=2, concat_axis=1)
    v = a2a(v, split_axis=2, concat_axis=1)
    out = jax.nn.dot_product_attention(q, k, v, is_causal=causal, scale=scale)
    return a2a(out, split_axis=1, concat_axis=2)


# ---------------------------------------------------------------------------
# shard_map wrappers.
# ---------------------------------------------------------------------------


def _cp_spec(mesh, seq_axis, batch_axes, head_axes):
    batch = tuple(a for a in batch_axes
                  if a in mesh.shape and mesh.shape[a] > 1)
    head = next((a for a in head_axes
                 if a in mesh.shape and mesh.shape[a] > 1), None)
    return _shardlib.spec(batch if batch else None, seq_axis, head, None)


def _ring_flash_shapes_ok(s_loc, d, balanced):
    """Whether the Pallas pos-kernels handle this per-shard problem (same
    VMEM envelope as flash_attention_supported, on the LOCAL length)."""
    if balanced and s_loc % 2:
        return False
    return (s_loc >= 128 and s_loc % 128 == 0 and d <= 256
            and s_loc * d <= (1 << 20))


def context_parallel_attention(q, k, v, mesh, *, mode="ring", seq_axis="sep",
                               causal=True, scale=None, impl=None,
                               balanced=None,
                               batch_axes=("dp", "sharding", "fsdp"),
                               head_axis=("mp", "tp")):
    """Sequence-sharded self-attention over `seq_axis` of `mesh`.

    q/k/v: [batch, seq, heads, head_dim] global arrays (or tracers inside a
    jit using `mesh`); seq must divide by mesh.shape[seq_axis]; with
    mode="ulysses", local heads must also divide by it.

    `impl` selects the ring step body: "einsum" (any shape), "flash" (the
    Pallas pos-kernels; per-shard length must be 128-aligned), or
    None/"auto" — flash on TPU when the shapes qualify, einsum otherwise.
    `mode="ring_flash"` is shorthand for mode="ring", impl="flash".
    `balanced` (default: on for causal ring when divisibility allows)
    applies the zigzag causal placement so every ring step does even work.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if mode == "ring_flash":
        mode, impl = "ring", "flash"
    head_axes = (head_axis,) if isinstance(head_axis, str) else head_axis
    spec = _cp_spec(mesh, seq_axis, batch_axes, head_axes)
    from ..compat import shard_map

    if mode == "ring":
        n = int(mesh.shape[seq_axis])
        b, s, h, d = q.shape
        if s % n:
            raise ValueError(f"seq len {s} must divide the {seq_axis!r} "
                             f"axis size {n}")
        s_loc = s // n
        # heads may additionally be sharded over the head axis; that does
        # not change s_loc/d so the flash qualification below holds
        if balanced is None:
            balanced = bool(causal) and n > 1 and s % (2 * n) == 0
        if impl in (None, "auto"):
            impl = "flash" if (jax.default_backend() == "tpu"
                               and _ring_flash_shapes_ok(s_loc, d, balanced)) \
                else "einsum"
        if impl == "flash":
            if not _ring_flash_shapes_ok(s_loc, d, balanced):
                raise ValueError(
                    f"ring flash needs a 128-aligned per-shard length "
                    f"(and head_dim <= 256), got seq {s} over "
                    f"{seq_axis}={n} -> {s_loc}, head_dim {d}")
            interpret = jax.default_backend() != "tpu"
            body = partial(_ring_flash_local, axis_name=seq_axis,
                           causal=causal, scale=scale, balanced=balanced,
                           interpret=interpret)
        elif impl == "einsum":
            body = partial(_ring_attention_local, axis_name=seq_axis,
                           causal=causal, scale=scale, balanced=balanced)
        else:
            raise ValueError(f"unknown ring impl {impl!r}")
    elif mode == "ulysses":
        balanced = False
        body = partial(_ulysses_attention_local, axis_name=seq_axis,
                       causal=causal, scale=scale)
    else:
        raise ValueError(f"unknown context-parallel mode {mode!r}")

    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    if balanced and mode == "ring":
        perm, inv = zigzag_permutation(q.shape[1], int(mesh.shape[seq_axis]))
        out = fn(q[:, perm], k[:, perm], v[:, perm])
        return out[:, inv]
    return fn(q, k, v)


def ring_attention(q, k, v, mesh, *, seq_axis="sep", causal=True, scale=None,
                   impl=None, balanced=None,
                   batch_axes=("dp", "sharding", "fsdp"),
                   head_axis=("mp", "tp")):
    """Ring attention (ppermute K/V rotation + online softmax)."""
    return context_parallel_attention(
        q, k, v, mesh, mode="ring", seq_axis=seq_axis, causal=causal,
        scale=scale, impl=impl, balanced=balanced, batch_axes=batch_axes,
        head_axis=head_axis)


def ulysses_attention(q, k, v, mesh, *, seq_axis="sep", causal=True,
                      scale=None, batch_axes=("dp", "sharding", "fsdp"),
                      head_axis=("mp", "tp")):
    """Ulysses all-to-all sequence/head-parallel attention."""
    return context_parallel_attention(
        q, k, v, mesh, mode="ulysses", seq_axis=seq_axis, causal=causal,
        scale=scale, batch_axes=batch_axes, head_axis=head_axis)


# ---------------------------------------------------------------------------
# Trace-time routing state: the engine enables this around its traced loss so
# model-level `F.scaled_dot_product_attention` calls transparently become
# context-parallel when the mesh has a sequence axis ("cp"/"sep") > 1.
# ---------------------------------------------------------------------------


class _CPState(threading.local):
    def __init__(self):
        self.config = None  # (mesh, mode, seq_axis)


_cp_state = _CPState()


def active_context_parallel():
    """(mesh, mode, seq_axis) if a context_parallel_guard is active."""
    return _cp_state.config


@contextlib.contextmanager
def context_parallel_guard(mesh, mode="ring", seq_axis="sep"):
    prev = _cp_state.config
    _cp_state.config = (mesh, mode, seq_axis)
    try:
        yield
    finally:
        _cp_state.config = prev
