"""Activation recompute (gradient checkpointing) + gradient merge.

Reference analog: `RecomputeFunction` / `recompute()`
(python/paddle/distributed/fleet/recompute/recompute.py:108,404) — a PyLayer
that saves only segment inputs + RNG state, replaying the forward inside
backward; and the gradient-merge meta optimizer
(fleet/meta_optimizers/gradient_merge_optimizer.py).

TPU-native redesign: under `to_static`/the parallel engine everything is one
jax trace, so recompute is literally `jax.checkpoint` — XLA rematerializes
the segment in the backward pass, trading MXU FLOPs for HBM. In eager mode a
recomputed Layer segment becomes ONE tape node (inputs-only residuals, jitted
VJP reruns the forward), which is exactly the reference's PyLayer contract.
RNG replay (the reference saves/restores CUDA RNG state) falls out of JAX's
functional PRNG: the segment derives its dropout keys from an explicit key
that is identical in replay.
"""
from __future__ import annotations

import jax

from ...core.dispatch import apply
from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from ...ops import random as rnd

__all__ = ["recompute", "recompute_sequential", "GradientMergeOptimizer"]


def _impl_for(layer: Layer, method=None):
    """One cached pure impl per (layer, method): primals are
    [rng_key, *param_vals, *buffer_vals, *inputs] so parameter gradients
    flow through the tape node, fresh dropout keys are drawn per call (the
    reference saves/replays RNG state per segment, recompute.py:108), and
    buffer updates (BN running stats) are returned and written back."""
    from ..functional import functionalize

    cache = layer.__dict__.setdefault("_recompute_impl_cache", {})
    ckey = method or "forward"
    entry = cache.get(ckey)
    if entry is not None:
        return entry

    apply_fn, params, buffers = functionalize(layer, method=method)
    pnames, bnames = list(params), list(buffers)
    np_, nb_ = len(pnames), len(bnames)
    meta = {"treedef": None}

    def impl(key, *vals):
        def seg(key, *xs):
            rnd.push_trace_key(key)
            try:
                out, new_buf = apply_fn(
                    dict(zip(pnames, xs[:np_])),
                    dict(zip(bnames, xs[np_:np_ + nb_])),
                    *[Tensor(x) for x in xs[np_ + nb_:]])
            finally:
                rnd.pop_trace_key()
            leaves, treedef = jax.tree_util.tree_flatten(out)
            meta["treedef"] = treedef  # static: set at trace time
            return tuple(leaves) + tuple(new_buf[n] for n in bnames)

        return jax.checkpoint(seg)(key, *vals)

    impl.__name__ = f"_recompute_{type(layer).__name__}"
    entry = (impl, params, buffers, meta)
    cache[ckey] = entry
    return entry


def recompute(function, *args, **kwargs):
    """Run `function(*args)` without storing its intermediate activations;
    the backward pass recomputes them (reference: recompute.py:404).

    `function` should be a Layer or a bound method of one (the reference's
    dominant usage, e.g. `recompute(self.block, x)`); its parameters get
    gradients through the recomputed segment. Inside `to_static`/engine
    traces, arbitrary callables work too (pure jax.checkpoint)."""
    kwargs.pop("preserve_rng_state", None)
    kwargs.pop("use_reentrant", None)
    if kwargs:
        raise TypeError(f"unsupported kwargs for recompute: {list(kwargs)}")

    from ...jit.api import _in_to_static
    if _in_to_static():
        # whole step is one jax trace: closed-over tracers (params) are
        # differentiated by the outer grad, so any callable is fine
        vals = [a._value if isinstance(a, Tensor) else a for a in args]

        def seg(*xs):
            outs = function(*[Tensor(x) if not isinstance(x, Tensor) else x
                              for x in xs])
            return outs._value if isinstance(outs, Tensor) else \
                jax.tree_util.tree_map(
                    lambda o: o._value if isinstance(o, Tensor) else o, outs)

        out = jax.checkpoint(seg)(*vals)
        return jax.tree_util.tree_map(Tensor, out)

    layer = None
    method = None
    if isinstance(function, Layer):
        layer = function
    elif isinstance(getattr(function, "__self__", None), Layer):
        layer = function.__self__
        method = function.__name__
    if layer is None:
        # plain eager callable: run through the tape (per-op inputs-only
        # residuals already bound activation memory); no single-node fusion
        return function(*args)

    impl, params, buffers, meta = _impl_for(layer, method)
    in_tensors = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
    primals = (rnd.next_key(),) + tuple(params.values()) + \
        tuple(buffers.values()) + tuple(in_tensors)
    outs = apply(f"recompute_{type(layer).__name__}", impl, primals)
    outs = outs if isinstance(outs, tuple) else (outs,)
    nb_ = len(buffers)
    out_leaves = outs[:len(outs) - nb_]
    for b, new in zip(buffers.values(), outs[len(outs) - nb_:]):
        b._value = new._value
    res = jax.tree_util.tree_unflatten(meta["treedef"], out_leaves)
    return res


class _Seg(Layer):
    """A contiguous recompute segment of a Sequential."""

    def __init__(self, mods):
        super().__init__()
        for i, m in enumerate(mods):
            self.add_sublayer(str(i), m)
        self._mods = mods

    def forward(self, *xs):
        for m in self._mods:
            xs = m(*xs) if isinstance(xs, tuple) else m(xs)
            if not isinstance(xs, tuple):
                xs = (xs,)
        return xs if len(xs) > 1 else xs[0]


# Segment layers are cached per (member identity, split): a fresh _Seg per
# call would miss the per-layer impl cache and retrace/compile every step.
# The cache lives ON the first member object itself, so its lifetime is the
# model's lifetime — dropping the model drops the segments with it (the
# member<->_Seg reference cycle is ordinary GC work). A global registry
# (weak or strong) cannot do this: the segments strongly reference their
# members, which would pin a weak key forever.
_seg_cache_fallback = {}  # anchors without a __dict__ (rare plain callables)


def recompute_sequential(ctx, functions, *args):
    """Recompute a Sequential in segments (reference:
    recompute_sequential / recompute_hybrid entry). ctx: {"segments": k}."""
    segments = int(ctx.get("segments", 1)) if isinstance(ctx, dict) else 1
    funcs = list(functions)
    n = len(funcs)
    seg_size = max(1, (n + segments - 1) // segments)
    key = (tuple(id(f) for f in funcs), seg_size)
    try:
        # bypass Layer.__setattr__: this is bookkeeping, not a sublayer
        per_anchor = funcs[0].__dict__.setdefault(
            "_recompute_seg_cache", {})
    except AttributeError:
        per_anchor = _seg_cache_fallback
    segs = per_anchor.get(key)
    if segs is None:
        segs = [_Seg(funcs[s:s + seg_size]) for s in range(0, n, seg_size)]
        per_anchor[key] = segs
    out = args
    for seg in segs:
        res = recompute(seg, *out)
        out = res if isinstance(res, tuple) else (res,)
    return out if len(out) > 1 else out[0]


class GradientMergeOptimizer:
    """Gradient accumulation wrapper (reference:
    fleet/meta_optimizers/gradient_merge_optimizer.py — accumulate grads
    for k_steps, then apply once). Eager tape grads already accumulate
    across backward() calls; this wrapper steps the inner optimizer every
    k-th call and averages if requested."""

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self.inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg
        self._count = 0

    @property
    def _parameter_list(self):
        return getattr(self.inner_opt, "_parameter_list", [])

    def step(self):
        self._count += 1
        if self._count % self.k_steps != 0:
            return False
        if self.avg and self.k_steps > 1:
            from ...ops.math import scale
            for p in self._parameter_list:
                if p.grad is not None:
                    p.grad = scale(p.grad, 1.0 / self.k_steps)
        self.inner_opt.step()
        return True

    def clear_grad(self, set_to_zero=False):
        # only clear after an actual apply (mid-accumulation grads persist)
        if self._count % self.k_steps == 0:
            self.inner_opt.clear_grad(set_to_zero) if _accepts_arg(
                self.inner_opt.clear_grad) else self.inner_opt.clear_grad()

    def __getattr__(self, name):
        return getattr(self.inner_opt, name)


def _accepts_arg(fn):
    import inspect
    try:
        return len(inspect.signature(fn).parameters) > 0
    except (TypeError, ValueError):
        return False
