"""Elastic membership manager.

Reference analog: `ElasticManager` (fleet/elastic/manager.py:126) — etcd
node registration with TTL leases + heartbeat threads (:251-264), peer-set
watching, scale in/out detection, and trainer relaunch with rewritten
endpoints. TPU-native: the native coordination store replaces etcd; leases
are heartbeat keys with server-side receipt ages; relaunch itself is the
launcher's elastic loop (launch/controller.py) — this manager provides the
membership/decision layer.
"""
from __future__ import annotations

import threading
import time

from ...analysis import locks as _locks
from ..store import TCPStore, Watchdog
# clean-preempt contract shared with the launcher: a worker that exits
# PREEMPT_EXIT_CODE checkpointed on purpose inside its grace window, and
# the elastic relaunch does NOT spend a retry on it (controller.run)
from ..preemption import PREEMPT_EXIT_CODE, is_clean_preempt  # noqa: F401


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"
    PREEMPT = "preempt"  # clean preemption — relaunch without burning a retry


class ElasticManager:
    def __init__(self, store: TCPStore, job_id="default", rank=0,
                 np_target=1, ttl=10.0, interval=1.0,
                 checkpoint_root=None, keep_last_k=3):
        self.store = store
        self.job_id = job_id
        self.rank = int(rank)
        self.np_target = int(np_target)  # desired world size
        self.ttl = float(ttl)
        self.interval = float(interval)
        self._member = f"{job_id}/node{rank}"
        self._watchdog = Watchdog(store, ttl=ttl, interval=interval)
        self._stop = threading.Event()
        self._lock = _locks.new_lock("fleet.elastic")
        self._status = ElasticStatus.HOLD
        self._thread = None
        # fault-tolerant resume: membership detects the failure, the
        # checkpoint manager supplies the state to restart from (the
        # reference couples its elastic manager to per-rank save_state_dict
        # the same way)
        self.checkpoint = None
        if checkpoint_root:
            from ..checkpoint.manager import CheckpointManager

            self.checkpoint = CheckpointManager(checkpoint_root,
                                                keep_last_k=keep_last_k)

    # -- membership --------------------------------------------------------
    def register(self):
        """Join the job: publish endpoint + start the heartbeat lease
        (reference: manager.py register + lease keepalive)."""
        self.store.set(f"/elastic/{self.job_id}/node/{self.rank}",
                       str(self.rank))
        self.store.start_heartbeat(self._member, interval=self.interval)

    def deregister(self):
        self.store.stop_heartbeat()
        self.store.delete_key(f"/elastic/{self.job_id}/node/{self.rank}")

    def alive_members(self):
        """Node names with fresh heartbeats."""
        out = []
        for m in self._watchdog.members():
            if not m.startswith(f"{self.job_id}/"):
                continue
            age = self.store.heartbeat_age(m)
            if age is not None and age <= self.ttl:
                out.append(m)
        return sorted(out)

    # -- scale detection ---------------------------------------------------
    def check(self):
        """One sweep: HOLD while converging, RESTART on scale in/out."""
        n = len(self.alive_members())
        if n == self.np_target:
            return ElasticStatus.HOLD
        return ElasticStatus.RESTART

    def watch(self, on_change=None):
        """Background watch; calls on_change(status, alive) on transitions
        out of HOLD (reference: manager.py watch loop)."""

        def loop():
            last = None
            while not self._stop.wait(self.interval):
                st = self.check()
                with self._lock:
                    self._status = st
                if st != ElasticStatus.HOLD and st != last and \
                        on_change is not None:
                    on_change(st, self.alive_members())
                last = st

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    @property
    def status(self):
        with self._lock:
            return self._status

    def wait_for_world(self, timeout=60.0):
        """Block until np_target members are alive (job convergence)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.alive_members()) == self.np_target:
                return True
            time.sleep(self.interval / 2)
        return False

    # -- fault-tolerant resume ---------------------------------------------
    def resume(self, state_dict):
        """Restore the newest committed checkpoint into `state_dict`
        (tensors in place, scalar leaves merged). Returns the restored
        step, or None when there is nothing to resume from — the restart
        path after a RESTART transition: relaunched trainers call this
        before their first step so a detected failure resumes instead of
        retraining from scratch. Torn checkpoints left by the crash are
        skipped by the manager's integrity checks."""
        if self.checkpoint is None:
            return None
        return self.checkpoint.restore_latest(state_dict)

    def save(self, state_dict, step, extra=None):
        """Checkpoint through the manager (atomic commit + rotation)."""
        if self.checkpoint is None:
            raise RuntimeError(
                "ElasticManager has no checkpoint_root configured")
        return self.checkpoint.save(state_dict, step, extra=extra)

    def preempt_save(self, state_dict, step, extra=None):
        """Grace-window save for a preemption notice: synchronous, and an
        in-flight async save is waited out first so no uncommitted staging
        dir is abandoned (CheckpointManager.preempt_save). Pair with
        `sys.exit(PREEMPT_EXIT_CODE)` so the launcher relaunches without
        spending an elastic retry."""
        if self.checkpoint is None:
            raise RuntimeError(
                "ElasticManager has no checkpoint_root configured")
        with self._lock:
            self._status = ElasticStatus.PREEMPT
        return self.checkpoint.preempt_save(state_dict, step, extra=extra)

    def exit(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._watchdog.stop()
