"""Fleet facade.

Reference: `paddle.distributed.fleet` — `fleet.init` (fleet/fleet.py:167),
`DistributedStrategy` (fleet/base/distributed_strategy.py:175),
`distributed_model` (fleet/model.py:32), `distributed_optimizer`
(hybrid_parallel_optimizer.py:254).

TPU-native: init builds the hybrid Mesh from `hybrid_configs`;
distributed_model/distributed_optimizer return the pieces the jitted
engine path uses (or a thin eager DataParallel for pure-DP eager use).
"""
from __future__ import annotations

from ..env import init_parallel_env, get_rank, get_world_size
from ..topology import (
    HybridCommunicateGroup, CommunicateTopology,
    set_hybrid_communicate_group, get_hybrid_communicate_group, build_mesh,
)
from ..engine import ShardedTrainStep, parallelize
from ..data_parallel import DataParallel
from ..random import get_rng_state_tracker, model_parallel_random_seed
from .distributed_strategy import DistributedStrategy
from .recompute import (
    recompute, recompute_sequential, GradientMergeOptimizer,
)

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """Reference: fleet.init (fleet/fleet.py:167)."""
    strategy = strategy or DistributedStrategy()
    init_parallel_env()
    hcg = HybridCommunicateGroup(strategy=strategy)
    set_hybrid_communicate_group(hcg)
    _fleet_state.update(strategy=strategy, hcg=hcg, initialized=True)
    return None


def is_initialized():
    return _fleet_state["initialized"]


def get_hybrid_communicate_group_():
    return _fleet_state["hcg"]


# surface parity: fleet.get_hybrid_communicate_group()
def _get_hcg():
    return _fleet_state["hcg"] or get_hybrid_communicate_group()


get_hybrid_communicate_group = _get_hcg


def distributed_model(model):
    """Reference: fleet/model.py:32 — picks the wrapper by parallel mode.
    On the mesh build, TP/sharding placement happens via sharding specs
    (sharding_spec.shard_params is applied by ShardedTrainStep /
    parallelize); the eager wrapper is only needed for pure data parallel."""
    hcg = _get_hcg()
    if hcg is None:
        raise RuntimeError("call fleet.init first")
    from ..sharding_spec import shard_params
    if hcg.get_model_parallel_world_size() > 1 or \
            hcg.get_sharding_parallel_world_size() > 1:
        stage = (_fleet_state["strategy"].hybrid_configs
                 .get("sharding_stage", 1)
                 if _fleet_state["strategy"] else 1)
        shard_params(model, hcg.mesh,
                     sharding_stage=stage
                     if hcg.get_sharding_parallel_world_size() > 1 else 0)
        return model
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """Reference: fleet.distributed_optimizer → HybridParallelOptimizer.
    The jitted engine handles cross-axis grad sync/clip inside the compiled
    step, so the optimizer passes through unchanged."""
    return optimizer


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()


class Role:
    """Reference: fleet/base/role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Reference: fleet/base/role_maker.py PaddleCloudRoleMaker — derives
    the process role from the launch env contract (here the PADDLE_TPU_*
    contract; every process is a collective worker on the mesh runtime)."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _is_worker(self):
        return True

    def _is_server(self):
        return False

    def _worker_index(self):
        from ..env import get_rank
        return get_rank()

    def _worker_num(self):
        from ..env import get_world_size
        return get_world_size()

    def _role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Reference: role_maker.py UserDefinedRoleMaker — explicit role/rank."""

    def __init__(self, is_collective=True, current_id=0, role=Role.WORKER,
                 worker_num=1, server_endpoints=None, **kwargs):
        super().__init__(is_collective)
        self._current_id = current_id
        self._role_val = role
        self._worker_num_val = worker_num

    def _worker_index(self):
        return self._current_id

    def _worker_num(self):
        return self._worker_num_val

    def _role(self):
        return self._role_val


class UtilBase:
    """Reference: fleet/base/util_factory.py UtilBase — small cross-rank
    utilities over the collective surface."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np
        from .. import collective as C
        from ...core.tensor import Tensor
        import jax.numpy as jnp
        t = Tensor(jnp.asarray(np.asarray(input)))
        op = {"sum": C.ReduceOp.SUM, "max": C.ReduceOp.MAX,
              "min": C.ReduceOp.MIN}[mode]
        C.all_reduce(t, op=op)
        return np.asarray(t._value)

    def barrier(self, comm_world="worker"):
        from .. import collective as C
        C.barrier()

    def all_gather(self, input, comm_world="worker"):
        from ..p2p import all_gather_object
        out = []
        all_gather_object(out, input)
        return out


class _FleetFacade:
    """Reference: fleet.Fleet (fleet/fleet.py) — the singleton facade
    class; module-level functions here are its bound methods."""

    def __init__(self):
        import sys
        self._mod = sys.modules[__name__]
        self.util = UtilBase()

    def __getattr__(self, name):
        return getattr(self._mod, name)


Fleet = _FleetFacade


class MultiSlotDataGenerator:
    """Reference: distributed/fleet/data_generator — stdin->slot-record
    pipe for the PS data feed. generate_sample yields
    [(slot_name, [ids...]), ...]; run_from_stdin prints the slot-record
    line format InMemoryDataset parses."""

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclass MultiSlotDataGenerator and implement generate_sample")

    def _format(self, record):
        toks = []
        for slot, vals in record:
            for v in vals:
                toks.append(f"{slot}:{v}")
        return " ".join(toks)

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            gen = self.generate_sample(line)
            for record in (gen() if callable(gen) else gen):
                sys.stdout.write(self._format(record) + "\n")

    def run_from_memory(self, lines):
        out = []
        for line in lines:
            gen = self.generate_sample(line)
            for record in (gen() if callable(gen) else gen):
                out.append(self._format(record))
        return out


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-valued slots variant (reference: data_generator)."""
