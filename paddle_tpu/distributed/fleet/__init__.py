"""Fleet facade.

Reference: `paddle.distributed.fleet` — `fleet.init` (fleet/fleet.py:167),
`DistributedStrategy` (fleet/base/distributed_strategy.py:175),
`distributed_model` (fleet/model.py:32), `distributed_optimizer`
(hybrid_parallel_optimizer.py:254).

TPU-native: init builds the hybrid Mesh from `hybrid_configs`;
distributed_model/distributed_optimizer return the pieces the jitted
engine path uses (or a thin eager DataParallel for pure-DP eager use).
"""
from __future__ import annotations

from ..env import init_parallel_env, get_rank, get_world_size
from ..topology import (
    HybridCommunicateGroup, CommunicateTopology,
    set_hybrid_communicate_group, get_hybrid_communicate_group, build_mesh,
)
from ..engine import ShardedTrainStep, parallelize
from ..data_parallel import DataParallel
from ..random import get_rng_state_tracker, model_parallel_random_seed
from .distributed_strategy import DistributedStrategy
from .recompute import (
    recompute, recompute_sequential, GradientMergeOptimizer,
)

_fleet_state = {"strategy": None, "hcg": None, "initialized": False}


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    """Reference: fleet.init (fleet/fleet.py:167)."""
    strategy = strategy or DistributedStrategy()
    init_parallel_env()
    hcg = HybridCommunicateGroup(strategy=strategy)
    set_hybrid_communicate_group(hcg)
    _fleet_state.update(strategy=strategy, hcg=hcg, initialized=True)
    return None


def is_initialized():
    return _fleet_state["initialized"]


def get_hybrid_communicate_group_():
    return _fleet_state["hcg"]


# surface parity: fleet.get_hybrid_communicate_group()
def _get_hcg():
    return _fleet_state["hcg"] or get_hybrid_communicate_group()


get_hybrid_communicate_group = _get_hcg


def distributed_model(model):
    """Reference: fleet/model.py:32 — picks the wrapper by parallel mode.
    On the mesh build, TP/sharding placement happens via sharding specs
    (sharding_spec.shard_params is applied by ShardedTrainStep /
    parallelize); the eager wrapper is only needed for pure data parallel."""
    hcg = _get_hcg()
    if hcg is None:
        raise RuntimeError("call fleet.init first")
    from ..sharding_spec import shard_params
    if hcg.get_model_parallel_world_size() > 1 or \
            hcg.get_sharding_parallel_world_size() > 1:
        stage = (_fleet_state["strategy"].hybrid_configs
                 .get("sharding_stage", 1)
                 if _fleet_state["strategy"] else 1)
        shard_params(model, hcg.mesh,
                     sharding_stage=stage
                     if hcg.get_sharding_parallel_world_size() > 1 else 0)
        return model
    return DataParallel(model)


def distributed_optimizer(optimizer, strategy=None):
    """Reference: fleet.distributed_optimizer → HybridParallelOptimizer.
    The jitted engine handles cross-axis grad sync/clip inside the compiled
    step, so the optimizer passes through unchanged."""
    return optimizer


def worker_index():
    return get_rank()


def worker_num():
    return get_world_size()
