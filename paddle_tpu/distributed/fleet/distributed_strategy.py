"""DistributedStrategy (reference: fleet/base/distributed_strategy.py:175 —
a protobuf of per-feature sub-configs). Plain attrs here; consumed by
fleet.init (hybrid_configs → mesh degrees) and the engine (amp/sharding/
recompute knobs)."""
from __future__ import annotations


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": -1,
            "mp_degree": 1,
            "pp_degree": 1,
            "sep_degree": 1,
            "sharding_degree": 1,
            "sharding_stage": 1,
        }
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16": False,
                            "custom_white_list": [], "custom_black_list": []}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1, "degree": 1}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.fuse_all_reduce_ops = True  # XLA always fuses; parity knob
        self.find_unused_parameters = False

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"
