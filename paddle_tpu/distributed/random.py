"""Per-axis RNG state tracking for dropout determinism under TP.

Reference: `RNGStatesTracker` (fleet/layers/mpu/random.py:34) — keeps a
named RNG state per parallel axis so e.g. dropout inside a TP block uses the
*same* mask on every mp rank but *different* masks across dp ranks.

TPU-native: JAX keys are values, not global state; we keep a named key per
tracker entry and fold the mesh axis index in when requested, so inside
shard_map a "local" generator differs per coordinate while "model-parallel"
ones stay identical.
"""
from __future__ import annotations

import contextlib

import jax

from ..ops import random as global_rng

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def add(self, name, seed):
        if name in self.states_:
            raise ValueError(f"state {name!r} already exists")
        self.states_[name] = jax.random.PRNGKey(int(seed))

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = dict(states)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name!r} does not exist")
        key, sub = jax.random.split(self.states_[name])
        self.states_[name] = key
        global_rng.push_trace_key(sub)
        try:
            yield
        finally:
            global_rng.pop_trace_key()


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    """Reference: mpu/random.py:84 — seed the global + per-axis states."""
    import numpy as np
    seed = int(seed if seed is not None else np.random.randint(0, 2 ** 31))
    _tracker.reset()
    global_rng.seed(seed + 100)
    _tracker.add(MODEL_PARALLEL_RNG, seed + 1)
