"""group_sharded_parallel facade (ZeRO levels by name).

Reference: python/paddle/distributed/sharding/group_sharded.py:44
`group_sharded_parallel(model, optimizer, level, ...)` which wraps the
model in GroupShardedStage2/3 and the optimizer in the sharded
optimizer, and `save_group_sharded_model`.

TPU-native: the ZeRO stages are *shardings*, not wrapper modules. The
facade places every parameter (and, through the train-step engine, every
optimizer slot) with the stage-appropriate NamedSharding over the
'sharding' mesh axis; XLA/GSPMD then derives the gather/reduce-scatter
traffic the reference's stage2/stage3 wrappers issue by hand. The model
and optimizer objects come back unwrapped — eager ops and the jitted
engine both see sharded arrays.
"""
from __future__ import annotations

import jax

from . import topology as topo_mod
from ..sharding import named_sharding as _named_sharding
from .sharding_spec import DEFAULT_TP_RULES, spec_for_param

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Returns (model, optimizer, scaler) with stage-`level` sharding
    applied. `level`: 'os' (ZeRO-1), 'os_g' (ZeRO-2), 'p_g_os' (ZeRO-3).

    `offload=True` parks parameters in host memory (jax memories API) —
    the analog of the reference's cpu_offload flag."""
    if level not in _LEVELS:
        raise ValueError(
            f"level must be one of {sorted(_LEVELS)}, got {level!r}")
    stage = _LEVELS[level]
    hcg = topo_mod.get_hybrid_communicate_group()
    if hcg is None:
        hcg = topo_mod.HybridCommunicateGroup(
            mesh=topo_mod.build_mesh(sharding=-1))
        topo_mod.set_hybrid_communicate_group(hcg)
    mesh = hcg.mesh

    for name, p in model.named_parameters():
        spec = spec_for_param(name, p, DEFAULT_TP_RULES,
                              sharding_stage=stage, mesh=mesh)
        sh = _named_sharding(mesh, spec)
        if offload:
            from ..compat import supports_memory_kind

            if supports_memory_kind("pinned_host"):
                sh = sh.with_memory_kind("pinned_host")
        p._value = jax.device_put(p._value, sh)
        p.dist_spec = tuple(spec)

    # The train-step engine reads this to shard grads (stage>=2) and
    # optimizer slots (stage>=1) the same way.
    optimizer._group_sharded_stage = stage
    model._group_sharded_stage = stage
    if scaler is not None:
        scaler._group_sharded = True
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Reference: sharding/group_sharded.py save_group_sharded_model —
    persists the (logically global) parameters; on the controller the
    sharded arrays already reassemble transparently."""
    import os
    from .. import framework_io
    os.makedirs(output, exist_ok=True)
    framework_io.save(model.state_dict(),
                      os.path.join(output, "model.pdparams"))
    if optimizer is not None and hasattr(optimizer, "state_dict"):
        framework_io.save(optimizer.state_dict(),
                          os.path.join(output, "model.pdopt"))
