"""DTensor surface: ProcessMesh + placements + shard/reshard.

See package docstring for the reference mapping. Everything here is thin by
design: the heavy machinery the reference implements by hand (SPMD rules,
reshard transforms, dist branches in every generated API) is delegated to
GSPMD/XLA. Cited parity points:
  - ProcessMesh           ≈ auto_parallel/process_mesh.py:71
  - Shard/Replicate/Partial ≈ auto_parallel/placement_type.py
  - shard_tensor          ≈ auto_parallel/api.py:118
  - dtensor_from_fn       ≈ auto_parallel/api.py:248
  - reshard               ≈ auto_parallel/api.py:282
  - shard_layer           ≈ auto_parallel/api.py:381
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from ...compat import shard_map
from jax.sharding import Mesh, NamedSharding  # isinstance checks only

from ... import sharding as _shardlib
from ...core.tensor import Tensor
from ...nn.layer.layers import Layer

__all__ = [
    "ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
    "dtensor_from_fn", "reshard", "shard_layer", "get_placements",
    "placements_to_spec",
]


# --------------------------------------------------------------------------
# Placements (reference: placement_type.py)
# --------------------------------------------------------------------------

class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Tensor dim `dim` is split over the corresponding mesh dimension."""

    def __init__(self, dim):
        self.dim = int(dim)

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending reduction over the mesh dim (reference: partial status with
    a reduce_type). Eagerly materialized as replicated-with-debt; the psum
    happens on reshard to Replicate/Shard."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type!r})"


# --------------------------------------------------------------------------
# ProcessMesh (reference: process_mesh.py:71)
# --------------------------------------------------------------------------

class ProcessMesh:
    """N-D grid of device/process ids with named dims. Owns the equivalent
    jax.sharding.Mesh; placements index its dims."""

    def __init__(self, mesh, dim_names=None, *, devices=None):
        arr = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} do not match mesh ndim {arr.ndim}")
        self._ids = arr
        self._dim_names = list(dim_names)
        devices = devices if devices is not None else jax.devices()
        ids = arr.reshape(-1).tolist()
        if len(set(ids)) != len(ids):
            raise ValueError(f"mesh has duplicate process ids: {sorted(ids)}")
        if ids and (min(ids) < 0 or max(ids) >= len(devices)):
            raise ValueError(
                f"mesh process ids span [{min(ids)}, {max(ids)}], but only "
                f"{len(devices)} devices are available")
        dev_arr = np.empty(arr.shape, dtype=object)
        for idx in np.ndindex(arr.shape):
            dev_arr[idx] = devices[int(arr[idx])]
        self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))

    @property
    def mesh(self):
        return self._ids

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def process_ids(self):
        return [int(x) for x in self._ids.flatten()]

    @property
    def jax_mesh(self) -> Mesh:
        return self._jax_mesh

    def get_dim_size(self, name):
        return self._ids.shape[self._dim_names.index(name)]

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")


def _as_jax_mesh(mesh):
    if isinstance(mesh, ProcessMesh):
        return mesh.jax_mesh
    if isinstance(mesh, Mesh):
        return mesh
    raise TypeError(f"expected ProcessMesh or jax Mesh, got {type(mesh)}")


# --------------------------------------------------------------------------
# placements <-> PartitionSpec
# --------------------------------------------------------------------------

def placements_to_spec(mesh, placements, ndim):
    """[per-mesh-dim placement] → PartitionSpec over tensor dims. A tensor
    dim sharded by several mesh dims gets a tuple entry (GSPMD multi-axis
    sharding), ordered by mesh dim."""
    jmesh = _as_jax_mesh(mesh)
    names = jmesh.axis_names
    if len(placements) != len(names):
        raise ValueError(
            f"need one placement per mesh dim ({len(names)}), "
            f"got {len(placements)}")
    entries = [[] for _ in range(ndim)]
    partials = {}
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim if pl.dim >= 0 else pl.dim + ndim
            if d < 0 or d >= ndim:
                raise ValueError(f"Shard dim {pl.dim} out of range for "
                                 f"ndim {ndim}")
            entries[d].append(names[mesh_dim])
        elif isinstance(pl, Partial):
            partials[names[mesh_dim]] = pl.reduce_type
        elif not isinstance(pl, (Replicate, type(None))):
            raise TypeError(f"unknown placement {pl!r}")
    spec = _shardlib.spec(*[
        None if not e else (e[0] if len(e) == 1 else tuple(e))
        for e in entries])
    return spec, partials


def _spec_to_placements(mesh, spec, ndim):
    jmesh = _as_jax_mesh(mesh)
    names = list(jmesh.axis_names)
    placements = [Replicate() for _ in names]
    entries = list(spec) + [None] * (ndim - len(list(spec)))
    for tdim, e in enumerate(entries):
        if e is None:
            continue
        for ax in ([e] if isinstance(e, str) else list(e)):
            placements[names.index(ax)] = Shard(tdim)
    return placements


def get_placements(tensor, mesh=None):
    """Placements of a (D)Tensor: from its jax sharding + any pending
    Partial annotation (reference: Tensor.placements)."""
    val = tensor._value if isinstance(tensor, Tensor) else tensor
    sharding = getattr(val, "sharding", None)
    mesh = mesh or getattr(sharding, "mesh", None)
    if mesh is None or not isinstance(sharding, NamedSharding):
        return None
    placements = _spec_to_placements(mesh, sharding.spec, val.ndim)
    partials = getattr(tensor, "_partial_axes", None) or {}
    names = list(_as_jax_mesh(mesh).axis_names)
    for ax, rt in partials.items():
        placements[names.index(ax)] = Partial(rt)
    return placements


# --------------------------------------------------------------------------
# shard_tensor / dtensor_from_fn / reshard / shard_layer
# --------------------------------------------------------------------------

def shard_tensor(data, mesh, placements, *, dtype=None, stop_gradient=None):
    """Create a distributed tensor from data + placements (reference:
    api.py:118). The result is an ordinary Tensor whose value carries a
    NamedSharding — every downstream op is GSPMD-partitioned."""
    t = data if isinstance(data, Tensor) else Tensor(np.asarray(data))
    jmesh = _as_jax_mesh(mesh)
    spec, partials = placements_to_spec(mesh, placements, t.ndim)
    if partials:
        raise ValueError(
            "shard_tensor cannot create a Partial tensor from data "
            "(the reference only produces partial tensors as op outputs); "
            "use Replicate() or Shard()")
    val = t._value
    if dtype is not None:
        from ...core.dtype import convert_dtype
        val = val.astype(convert_dtype(dtype))
    out = Tensor(jax.device_put(val, _shardlib.named_sharding(jmesh, spec)))
    out.stop_gradient = (t.stop_gradient if stop_gradient is None
                         else stop_gradient)
    out.process_mesh = mesh if isinstance(mesh, ProcessMesh) else None
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """Build the tensor with `fn` then place it (reference: api.py:248).
    On TPU the interesting case — creating the value already-sharded so no
    host copy of the global tensor exists — is handled by jax.jit with
    out_shardings."""
    jmesh = _as_jax_mesh(mesh)

    def call():
        out = fn(*args, **kwargs)
        return out._value if isinstance(out, Tensor) else out

    probe = jax.eval_shape(call)
    spec, partials = placements_to_spec(mesh, placements, len(probe.shape))
    if partials:
        raise ValueError("dtensor_from_fn cannot produce Partial outputs")
    val = jax.jit(call,
                  out_shardings=_shardlib.named_sharding(jmesh, spec))()
    out = Tensor(val)
    out.process_mesh = mesh if isinstance(mesh, ProcessMesh) else None
    return out


def reshard(tensor, mesh, placements):
    """Change placements (reference: api.py:282 + the C++ reshard rule zoo
    r_to_s/s_to_r/p_to_r/…). All source→target pairs collapse to:
      1. pending Partial? psum over those axes (p_to_r / p_to_s),
      2. device_put to the target NamedSharding (XLA moves the bytes —
         slice for r_to_s, all-gather for s_to_r, collective-permute for
         s_to_s')."""
    if not isinstance(tensor, Tensor):
        tensor = Tensor(tensor)
    jmesh = _as_jax_mesh(mesh)
    spec, target_partials = placements_to_spec(mesh, placements, tensor.ndim)
    val = tensor._value
    pending = dict(getattr(tensor, "_partial_axes", None) or {})
    # resolve pending partials the target doesn't keep
    resolve = [ax for ax in pending if ax not in target_partials]
    if resolve:
        cur = val.sharding.spec if isinstance(val.sharding, NamedSharding) \
            else _shardlib.spec(*([None] * val.ndim))

        def body(v):
            for ax in resolve:
                v = jax.lax.psum(v, ax)
            return v

        val = shard_map(
            body, mesh=jmesh, in_specs=cur, out_specs=cur,
            check_vma=False)(val)
        for ax in resolve:
            pending.pop(ax)
    val = jax.device_put(val, _shardlib.named_sharding(jmesh, spec))
    new_partials = [ax for ax in target_partials if ax not in pending]
    if new_partials:
        # r_to_p: the value survives only on coordinate 0 of each new
        # partial axis, other shards hold zeros — so p_to_r's psum later
        # reproduces the original value (reference r_to_p_reshard_function)
        def zero_rest(v):
            for ax in new_partials:
                idx = jax.lax.axis_index(ax)
                v = jnp.where(idx == 0, v, jnp.zeros_like(v))
            return v

        val = shard_map(zero_rest, mesh=jmesh, in_specs=spec,
                        out_specs=spec, check_vma=False)(val)
    out = Tensor(val)
    out.stop_gradient = tensor.stop_gradient
    if pending or target_partials:
        out._partial_axes = {**pending, **target_partials}
    out.process_mesh = mesh if isinstance(mesh, ProcessMesh) else None
    return out


def shard_layer(layer: Layer, mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Shard a layer's parameters in-place (reference: api.py:381).

    shard_fn(sublayer_name, sublayer, mesh) places each sublayer's params
    (via shard_tensor); default replicates everything on the mesh. input_fn/
    output_fn wrap forward to place activations."""
    jmesh = _as_jax_mesh(mesh)

    def default_shard_fn(name, sub, mesh):
        for pname, p in list(sub._parameters.items()):
            n = len(jmesh.axis_names)
            placed = shard_tensor(p, mesh, [Replicate()] * n)
            p._value = placed._value

    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda lyr, inputs: input_fn(inputs, mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda lyr, inputs, outputs: output_fn(outputs, mesh))
    return layer
