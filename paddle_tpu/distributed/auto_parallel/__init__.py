"""Auto-parallel (DTensor) API.

Reference analog: python/paddle/distributed/auto_parallel/ — `ProcessMesh`
(process_mesh.py:71), `shard_tensor`/`dtensor_from_fn`/`reshard`/
`shard_layer` (api.py:118,248,282,381), placements (placement_type.py:
Shard/Replicate/Partial), backed by C++ `DistTensor` + `TensorDistAttr`
+ per-op SPMD rules (phi/infermeta/spmd_rules/) + hand-written reshard
functions (phi/core/distributed/auto_parallel/reshard/).

TPU-native redesign: a DTensor IS a jax.Array with a NamedSharding — the
placements vector maps 1:1 onto a PartitionSpec over the ProcessMesh's
jax Mesh. SPMD propagation (the reference's per-op InferSpmd) is done by
GSPMD inside XLA; resharding (the reference's r_to_s/s_to_r/p_to_r rule
zoo) is a device_put / with_sharding_constraint — XLA emits the
collective-permute/all-gather/reduce-scatter. Only `Partial` needs real
code here (eager psum on reshard-to-replicate), because jax has no eager
partial placement.
"""
from .api import (  # noqa: F401
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, dtensor_from_fn,
    reshard, shard_layer, get_placements, placements_to_spec,
)
from .planner import (  # noqa: F401,E402
    plan, auto_parallelize, ModelStats, Plan,
    tune, auto_parallelize_tuned, TunedPlan, Measurement,
)
