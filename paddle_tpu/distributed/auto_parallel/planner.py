"""Auto-parallel planner: choose dp/mp/pp/sharding degrees from a cost
model.

Reference analog: the static auto-parallel Engine's Planner/completer +
cost model + auto-tuner
(python/paddle/distributed/auto_parallel/static/planner_v2.py,
static/cost/estimate_cost.py, auto_tuner/tuner.py) — which searches
process-mesh assignments against a cluster model.

TPU-native redesign: on a mesh runtime the *entire* search space is the
tuple of axis degrees (dp, mp, pp, sharding, sep) whose product is the
chip count — GSPMD derives everything below that. So the planner is an
explicit enumerate-and-score over divisor tuples:

- memory model: params + grads + optimizer moments + activations per
  chip under the candidate's sharding/tp/pp/sp splits (recompute
  discounts activations), must fit HBM;
- time model per step: MXU compute (6*N*tokens / peak) + DP/sharding
  gradient reduce-scatter+all-gather volume + TP per-block all-reduces
  + the PP bubble fraction — volumes priced over ICI bandwidth;
- the best-scoring feasible candidate becomes a Plan, which `apply()`
  turns into the hybrid mesh + engine kwargs.

Deliberately a closed-form analytic model (the reference simulates op
graphs): chip-count-scale search spaces are tiny, and the analytic form
makes every choice auditable in the Plan's rationale.
"""
from __future__ import annotations

import dataclasses
import math

import numpy as np

# v5e-class defaults; override per cluster.
DEFAULT_CHIP = dict(
    hbm_bytes=16e9,
    peak_flops=197e12,        # bf16
    ici_bandwidth=4.5e10,     # per-link bytes/s, one direction
)


@dataclasses.dataclass
class ModelStats:
    """What the cost model needs to know about the workload."""

    n_params: float
    num_layers: int
    hidden_size: int
    batch_size: int
    seq_len: int
    vocab_size: int = 0
    param_bytes: int = 2          # bf16 master-compute params
    grad_bytes: int = 2
    opt_state_bytes: int = 8      # adam: two fp32 moments
    act_bytes: int = 2
    recompute: bool = True

    @classmethod
    def from_model(cls, model, batch_size, seq_len, **kw):
        n = 0
        for _, p in model.named_parameters():
            n += int(np.prod(p.shape))
        cfg = getattr(model, "cfg", None)
        return cls(n_params=float(n),
                   num_layers=int(getattr(cfg, "num_layers", 1) or 1),
                   hidden_size=int(getattr(cfg, "hidden_size", 1) or 1),
                   vocab_size=int(getattr(cfg, "vocab_size", 0) or 0),
                   batch_size=batch_size, seq_len=seq_len, **kw)


@dataclasses.dataclass
class Candidate:
    dp: int
    mp: int
    pp: int
    sharding: int
    sep: int
    mem_per_chip: float
    step_time: float
    breakdown: dict
    microbatches: int = 1

    @property
    def degrees(self):
        return dict(dp=self.dp, mp=self.mp, pp=self.pp,
                    sharding=self.sharding, sep=self.sep)


class Plan:
    def __init__(self, best: Candidate, candidates, stats, chip):
        self.best = best
        self.candidates = candidates
        self.stats = stats
        self.chip = chip

    @property
    def degrees(self):
        return self.best.degrees

    @property
    def sharding_stage(self):
        return 2 if self.best.sharding > 1 else 0

    def apply(self):
        """Build the hybrid mesh + HCG for the chosen degrees."""
        from .. import topology as topo_mod
        mesh = topo_mod.build_mesh(**self.degrees)
        hcg = topo_mod.HybridCommunicateGroup(mesh=mesh)
        topo_mod.set_hybrid_communicate_group(hcg)
        return hcg

    def rationale(self):
        b = self.best
        lines = [
            f"chose dp={b.dp} mp={b.mp} pp={b.pp} sharding={b.sharding} "
            f"sep={b.sep} microbatches={b.microbatches}",
            f"est memory/chip: {b.mem_per_chip / 1e9:.2f} GB "
            f"(HBM {self.chip['hbm_bytes'] / 1e9:.0f} GB)",
            f"est step time: {b.step_time * 1e3:.1f} ms "
            f"({', '.join(f'{k}={v * 1e3:.1f}ms' for k, v in b.breakdown.items())})",
            f"rejected {len(self.candidates) - 1} feasible alternatives",
        ]
        return "\n".join(lines)


def _divisor_tuples(n, max_axes_vals):
    """All (dp, mp, pp, sharding, sep) with product == n, each axis
    bounded by max_axes_vals."""
    out = []
    axes = ["dp", "mp", "pp", "sharding", "sep"]

    def rec(i, remaining, cur):
        if i == len(axes) - 1:
            if remaining <= max_axes_vals[axes[i]]:
                out.append(cur + [remaining])
            return
        d = 1
        while d <= remaining:
            if remaining % d == 0 and d <= max_axes_vals[axes[i]]:
                rec(i + 1, remaining // d, cur + [d])
            d += 1

    rec(0, n, [])
    return [tuple(t) for t in out]


def _score(stats: ModelStats, chip, dp, mp, pp, sharding, sep,
           microbatches):
    """(mem_per_chip, step_time, breakdown) for one candidate."""
    N = stats.n_params
    data_ways = dp * sharding
    tokens = stats.batch_size * stats.seq_len

    if stats.batch_size % data_ways or stats.seq_len % sep:
        return None
    if stats.num_layers % pp:
        return None

    # ---- memory ------------------------------------------------------
    model_shard = mp * pp            # tensor+pipeline split of weights
    params = N * stats.param_bytes / model_shard
    params_resident = params / (sharding if sharding > 1 else 1)
    grads = N * stats.grad_bytes / model_shard / \
        (sharding if sharding > 1 else 1)
    opt = N * stats.opt_state_bytes / model_shard / \
        (sharding if sharding > 1 else 1)
    # activations: one transformer stack's worth for the local microbatch
    # (microbatches = gradient accumulation on non-pp plans, the 1F1B
    # chunking on pp plans — both bound live activations the same way)
    layers_local = stats.num_layers / pp
    mb = max(1, microbatches)
    act_tokens = tokens / data_ways / sep / mb
    act_factor = 2 if stats.recompute else 14  # remat keeps ~layer inputs
    acts = (act_tokens * stats.hidden_size * stats.act_bytes
            * layers_local * act_factor / mp)
    # pp keeps in-flight microbatch activations (1F1B: <= pp stages)
    if pp > 1:
        acts *= min(pp, mb)
    mem = params_resident + grads + opt + acts

    # ---- time --------------------------------------------------------
    bw = chip["ici_bandwidth"]
    flops = 6.0 * N * tokens
    n_chips = dp * mp * pp * sharding * sep
    t_compute = flops / (n_chips * chip["peak_flops"] * 0.5)

    # dp+sharding gradient sync: reduce-scatter + all-gather ring
    g_bytes = N * stats.grad_bytes / model_shard
    t_dp = (2.0 * (data_ways - 1) / max(data_ways, 1)) * g_bytes / bw \
        if data_ways > 1 else 0.0
    # tp: 2 all-reduces (attn + mlp) of activations per layer, fwd+bwd
    if mp > 1:
        a_bytes = (tokens / data_ways / sep) * stats.hidden_size \
            * stats.act_bytes
        t_tp = 4.0 * stats.num_layers * 2.0 * (mp - 1) / mp * a_bytes / bw
    else:
        t_tp = 0.0
    # sep: all-gather/reduce-scatter around attention blocks
    if sep > 1:
        a_bytes = (tokens / data_ways) * stats.hidden_size * stats.act_bytes
        t_sp = 2.0 * stats.num_layers * (sep - 1) / sep * a_bytes / bw
    else:
        t_sp = 0.0
    # pp bubble: (pp-1)/mb of the compute
    t_bubble = t_compute * (pp - 1) / mb if pp > 1 else 0.0

    t = t_compute + t_dp + t_tp + t_sp + t_bubble
    return mem, t, dict(compute=t_compute, dp=t_dp, tp=t_tp, sp=t_sp,
                        bubble=t_bubble)


def plan(model=None, stats: ModelStats | None = None, *, n_devices=None,
         batch_size=None, seq_len=None, chip=None, microbatches=4,
         max_mp=8, max_pp=None, allow_sep=False):
    """Search degree assignments; returns the best feasible Plan.

    Raises if nothing fits HBM (the reference tuner errors the same way
    when no distributed strategy satisfies memory)."""
    import jax

    chip = {**DEFAULT_CHIP, **(chip or {})}
    if n_devices is None:
        n_devices = jax.device_count()
    if stats is None:
        if model is None or batch_size is None or seq_len is None:
            raise ValueError("pass stats= or (model, batch_size, seq_len)")
        stats = ModelStats.from_model(model, batch_size, seq_len)

    bounds = dict(dp=n_devices, mp=max_mp,
                  pp=max_pp or stats.num_layers,
                  sharding=n_devices,
                  sep=(stats.seq_len if allow_sep else 1))
    feasible = []
    for dp, mp, pp, sharding, sep in _divisor_tuples(n_devices, bounds):
        # microbatch count joins the search: more accumulation chunks
        # bound activation memory at the cost of smaller per-step matmuls
        local_batch = stats.batch_size // max(dp * sharding, 1)
        mb = max(1, microbatches)
        while mb <= max(local_batch, 1):
            scored = _score(stats, chip, dp, mp, pp, sharding, sep, mb)
            if scored is not None:
                mem, t, br = scored
                if mem <= chip["hbm_bytes"] * 0.92:  # runtime headroom
                    feasible.append(Candidate(dp, mp, pp, sharding, sep,
                                              mem, t, br, mb))
                    break
            mb *= 2
    if not feasible:
        raise RuntimeError(
            f"no parallel plan fits {chip['hbm_bytes']/1e9:.0f} GB HBM on "
            f"{n_devices} chips for {stats.n_params/1e9:.2f}B params — "
            f"add chips, shrink the batch, or enable recompute")
    feasible.sort(key=lambda c: c.step_time)
    return Plan(feasible[0], feasible, stats, chip)


def auto_parallelize(model, optimizer=None, loss_fn=None, *, batch_size,
                     seq_len, chip=None, microbatches=4, **kw):
    """plan() + apply() + engine construction in one call (the reference
    Engine's `auto` mode: engine.prepare with strategy.auto_mode)."""
    from ..engine import parallelize as _parallelize

    p = plan(model=model, n_devices=None, batch_size=batch_size,
             seq_len=seq_len, chip=chip, microbatches=microbatches)
    hcg = p.apply()
    step = _parallelize(model, optimizer, loss_fn=loss_fn, mesh=hcg.mesh,
                        sharding_stage=p.sharding_stage, **kw)
    step.plan = p
    return step


# ---------------------------------------------------------------------------
# Measurement-driven tuner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Measurement:
    candidate: Candidate
    step_time: float            # measured seconds (mean of pipelined iters)
    predicted: float            # analytic model's estimate


class TunedPlan(Plan):
    """A Plan whose winner was chosen by MEASURING candidates, not by
    trusting the analytic model (reference:
    distributed/auto_parallel/static/tuner/parallel_tuner.py:36 — the
    ParallelTuner compiles+profiles candidate dist programs; here a
    candidate is a mesh-degree tuple and 'profile' is timing the compiled
    train step on the live devices)."""

    def __init__(self, best, candidates, stats, chip, measurements,
                 calibration):
        super().__init__(best, candidates, stats, chip)
        self.measurements = measurements
        self.calibration = calibration      # measured/analytic time ratio

    def rationale(self):
        lines = [super().rationale(),
                 f"measured {len(self.measurements)} candidates "
                 f"(calibration x{self.calibration:.2f} vs analytic):"]
        for m in self.measurements:
            d = m.candidate.degrees
            lines.append(
                f"  dp={d['dp']} mp={d['mp']} pp={d['pp']} "
                f"sharding={d['sharding']}: measured "
                f"{m.step_time * 1e3:.1f} ms (analytic "
                f"{m.predicted * 1e3:.1f} ms)")
        return "\n".join(lines)


def _time_train_step(step, batch, warmup=1, iters=2):
    """Mean wall time of step.train_batch over `iters` pipelined steps.
    Fences through the loss readback (float(...)) — block_until_ready can
    return at enqueue time through a PJRT relay, a host readback cannot.
    The fence sits OUTSIDE the timed loop so per-call dispatch latency
    (~tens of ms through a relay) amortizes instead of being billed to
    every step — the same methodology as bench.py."""
    import time

    def run():
        return (step.train_batch(*batch) if isinstance(batch, tuple)
                else step.train_batch(batch))

    for _ in range(warmup):
        float(run())
    t0 = time.perf_counter()
    loss = None
    for _ in range(iters):
        loss = run()
    float(loss)
    return (time.perf_counter() - t0) / iters


def tune(model, optimizer=None, loss_fn=None, *, batch_size, seq_len,
         sample_batch, top_k=3, chip=None, microbatches=4, n_devices=None,
         warmup=1, iters=2, stats=None, **kw):
    """Analytic plan() proposes top-k candidates; compile-and-time disposes.

    sample_batch: () -> batch (a Tensor or tuple of Tensors) accepted by the
    engine's train_batch for this model. Each candidate's mesh is built, the
    full train step compiled on the live devices (real chip, or the virtual
    CPU mesh under XLA_FLAGS=--xla_force_host_platform_device_count), and
    the fastest measured candidate wins. The measured/analytic ratio is
    returned as `calibration` so subsequent analytic-only planning can be
    scaled to this cluster (the reference ParallelTuner persists the same
    kind of profiled cost data).
    """
    from .. import topology as topo_mod
    from ..engine import parallelize as _parallelize

    p = plan(model=model, stats=stats, n_devices=n_devices,
             batch_size=batch_size, seq_len=seq_len, chip=chip,
             microbatches=microbatches)
    seen = set()
    cands = []
    for c in p.candidates:
        key = tuple(sorted(c.degrees.items()))
        if key not in seen:
            seen.add(key)
            cands.append(c)
        if len(cands) >= top_k:
            break

    prev_hcg = topo_mod.get_hybrid_communicate_group()
    # measuring runs REAL train steps: snapshot the live weights (and any
    # optimizer accumulators) so planning never mutates a trained model —
    # the reference ParallelTuner profiles on a throwaway program the same
    # way (parallel_tuner.py measures cloned dist_contexts)
    # snapshots live on the HOST: the engine donates device buffers into
    # the compiled step, so device-array references would be deleted by the
    # first measured step
    param_snap = {n: np.asarray(p._value)
                  for n, p in model.named_parameters()}
    buf_snap = {n: np.asarray(b._value) for n, b in model.named_buffers()}
    opt_state_attrs = {}
    if optimizer is not None:
        for attr, val in vars(optimizer).items():
            if isinstance(val, dict):
                opt_state_attrs[attr] = dict(val)
    measurements = []
    try:
        for c in cands:
            mesh = topo_mod.build_mesh(**c.degrees)
            hcg = topo_mod.HybridCommunicateGroup(mesh=mesh)
            topo_mod.set_hybrid_communicate_group(hcg)
            step = _parallelize(
                model, optimizer, loss_fn=loss_fn, mesh=mesh,
                sharding_stage=2 if c.sharding > 1 else 0, **kw)
            batch = sample_batch()
            t = _time_train_step(step, batch, warmup=warmup, iters=iters)
            measurements.append(Measurement(c, t, c.step_time))
            import jax.numpy as jnp
            for pname, param in model.named_parameters():
                param._value = jnp.asarray(param_snap[pname])
            for bname, buf in model.named_buffers():
                buf._value = jnp.asarray(buf_snap[bname])
            if optimizer is not None:
                for attr, val in opt_state_attrs.items():
                    setattr(optimizer, attr, dict(val))
    finally:
        topo_mod.set_hybrid_communicate_group(prev_hcg)

    measurements.sort(key=lambda m: m.step_time)
    best = measurements[0].candidate
    ratios = sorted(m.step_time / max(m.predicted, 1e-9)
                    for m in measurements)
    calibration = ratios[len(ratios) // 2]
    return TunedPlan(best, p.candidates, p.stats, p.chip, measurements,
                     calibration)


def auto_parallelize_tuned(model, optimizer=None, loss_fn=None, *,
                           batch_size, seq_len, sample_batch, top_k=3,
                           chip=None, warmup=1, iters=2, **kw):
    """tune() + apply() + fresh engine on the winning mesh."""
    from ..engine import parallelize as _parallelize

    tp = tune(model, optimizer, loss_fn=loss_fn, batch_size=batch_size,
              seq_len=seq_len, sample_batch=sample_batch, top_k=top_k,
              chip=chip, warmup=warmup, iters=iters, **kw)
    hcg = tp.apply()
    step = _parallelize(model, optimizer, loss_fn=loss_fn, mesh=hcg.mesh,
                        sharding_stage=tp.sharding_stage, **kw)
    step.plan = tp
    return step
