"""Multi-server parameter-server service: N server processes each owning a
table shard, with server-side optimizers, checkpoint/restore and
kill-a-server recovery.

Reference analogs:
- brpc PS server/service hosting sharded tables
  (paddle/fluid/distributed/ps/service/brpc_ps_server.h) — here a
  length-prefixed-pickle TCP protocol served by a thread-per-connection
  loop (the data plane is host-side numpy; TPU work stays in XLA, so a
  Python socket server is the right weight for this IO-bound tier).
- memory_sparse_table (ps/table/memory_sparse_table.h): lazily-initialized
  rows keyed by id, server-side sgd/adagrad apply, shrink/save/load.
- The row→server mapping is the reference's mod sharding
  (ps/table/table.h shard_num semantics): server_of(id) = id % num_servers.

Control plane: the native coord store (distributed/store.py) publishes
`ps/<name>/server/<i>` endpoints; a restarted server re-registers (new
port, bumped epoch) and clients re-resolve on connection failure — the
recovery story brpc gets from its naming service.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

import numpy as np

from ..analysis import locks as _locks

__all__ = ["SparseTableShard", "PsServer", "PsClient", "serve_shard"]


# --------------------------------------------------------------------------
# framed pickle transport
#
# SECURITY SCOPE: pickle deserialization executes arbitrary code, so this
# transport is strictly for loopback / single-tenant trusted cluster
# networks (the default host everywhere in this module is 127.0.0.1, and
# the launcher only ever wires workers to their own pod's servers). Never
# expose a PsServer port to untrusted peers; a hardened deployment would
# swap this codec for the brpc/protobuf service the reference uses.
# --------------------------------------------------------------------------

def _send_msg(sock, obj):
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    (n,) = struct.unpack("!I", _recv_exact(sock, 4))
    return pickle.loads(_recv_exact(sock, n))


# --------------------------------------------------------------------------
# table shard
# --------------------------------------------------------------------------

class SparseTableShard:
    """One server's shard of a sparse embedding table.

    Rows are created lazily on first touch with a per-id deterministic
    initializer (so a re-created shard reproduces untrained rows exactly,
    and the single-process parity reference can mirror initialization).
    The optimizer applies SERVER-side (reference: memory_sparse_table's
    sgd rule objects), so trainers only ship gradients.
    """

    def __init__(self, embedding_dim, optimizer="adagrad",
                 learning_rate=0.05, init_std=None, seed=0):
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError("optimizer must be 'sgd' or 'adagrad'")
        self.dim = int(embedding_dim)
        self.optimizer = optimizer
        self.lr = float(learning_rate)
        self.std = (float(init_std) if init_std is not None
                    else 1.0 / max(1.0, np.sqrt(self.dim)))
        self.seed = int(seed)
        self.rows: dict = {}
        self.accum: dict = {}
        self.lock = _locks.new_lock("ps.shard")
        self.applied_pushes = 0
        # exactly-once pushes: last applied sequence number per client
        # (a retried PUSH after a dropped response must not re-apply —
        # the brpc stack gets this from request ids; we persist it with
        # the shard so restarts keep the guarantee).
        #
        # CHECKPOINT-FRESHNESS CAVEAT: the dedup table is only as fresh as
        # the checkpoint it was restored from. A server restored from a
        # checkpoint older than its crash re-applies any push that was
        # (a) applied after that checkpoint and (b) retried by a client
        # after the restart — across a restore the guarantee degrades to
        # at-least-once for that window. Checkpoint after bursts of
        # applied pushes (PsClient.save) to keep the window small.
        self.applied_seq: dict = {}
        # last-activity clock per client, for pruning entries of clients
        # that have gone away (bounded memory on long-lived servers)
        self.seq_seen: dict = {}

    def _init_row(self, uid):
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + int(uid)) & 0x7FFFFFFF)
        return rng.normal(0.0, self.std, self.dim).astype(np.float32)

    def pull(self, uids):
        uids = np.asarray(uids, np.int64).ravel()
        with self.lock:
            out = np.empty((len(uids), self.dim), np.float32)
            for i, u in enumerate(uids):
                u = int(u)
                row = self.rows.get(u)
                if row is None:
                    row = self.rows[u] = self._init_row(u)
                out[i] = row
        return out

    def push(self, uids, grads, lr=None, client=None, seq=None):
        """Server-side optimizer apply; duplicate ids within one push are
        merged first (the reference merges by key before table apply).
        (client, seq) deduplicates retried pushes: a seq at or below the
        last applied one for that client is acknowledged without applying."""
        uids = np.asarray(uids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(uids), self.dim)
        lr = self.lr if lr is None else float(lr)
        uniq, inv = np.unique(uids, return_inverse=True)
        merged = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(merged, inv, grads)
        with self.lock:
            if client is not None and seq is not None:
                self.seq_seen[client] = time.monotonic()
                if seq <= self.applied_seq.get(client, -1):
                    return  # duplicate of an already-applied push
                self.applied_seq[client] = seq
            for i, u in enumerate(uniq):
                u = int(u)
                row = self.rows.get(u)
                if row is None:
                    row = self.rows[u] = self._init_row(u)
                g = merged[i]
                if self.optimizer == "adagrad":
                    acc = self.accum.get(u, 0.0) + float(g @ g)
                    self.accum[u] = acc
                    row -= lr / np.sqrt(acc + 1e-10) * g
                else:
                    row -= lr * g
            self.applied_pushes += 1

    def prune_idle_clients(self, idle_s=3600.0):
        """Drop applied_seq entries for clients silent longer than
        `idle_s` (a trainer that exited leaves its entry behind forever
        otherwise). Safe: a pruned client that somehow retries later is
        treated as new — its push re-applies, which is the same
        at-least-once degradation the checkpoint-freshness caveat above
        already documents. Returns the pruned client ids."""
        cutoff = time.monotonic() - float(idle_s)
        with self.lock:
            idle = [c for c, ts in self.seq_seen.items() if ts < cutoff]
            for c in idle:
                self.applied_seq.pop(c, None)
                self.seq_seen.pop(c, None)
        return idle

    # -- persistence (reference: table save/load in the PS service) --------
    def save(self, path, prune_idle_s=3600.0):
        if prune_idle_s is not None:
            self.prune_idle_clients(prune_idle_s)
        with self.lock:
            # serialize WHILE holding the lock: each connection runs on
            # its own thread, so a dump over live dicts/arrays outside it
            # could tear (rows mutated in place mid-pickle, applied_seq
            # recording a push whose row update is absent) or crash on
            # dict-resize during iteration
            # seq_seen is deliberately NOT saved: its values are this
            # process's time.monotonic() stamps, meaningless anywhere
            # else — load() rebuilds it from applied_seq keys
            state = {"dim": self.dim, "optimizer": self.optimizer,
                     "lr": self.lr, "std": self.std, "seed": self.seed,
                     "rows": self.rows, "accum": self.accum,
                     "applied_pushes": self.applied_pushes,
                     "applied_seq": self.applied_seq}
            blob = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        from .._atomic_io import atomic_write

        # atomic + fsynced + unique staging: a killed save can't corrupt
        # and concurrent savers can't clobber each other's temp file
        atomic_write(path, lambda f: f.write(blob))

    def load(self, path):
        with open(path, "rb") as f:
            state = pickle.load(f)
        with self.lock:
            self.dim = state["dim"]
            self.optimizer = state["optimizer"]
            self.lr = state["lr"]
            self.std = state["std"]
            self.seed = state["seed"]
            self.rows = state["rows"]
            self.accum = state["accum"]
            self.applied_pushes = state.get("applied_pushes", 0)
            self.applied_seq = state.get("applied_seq", {})
            # re-stamp EVERY client at load time: persisted stamps come
            # from another process's monotonic clock (a different, and
            # pre-fix a wall, clock domain) so comparing them against
            # this process's idle cutoff would be garbage — a loaded
            # client earns pruning only by being idle from now on
            now = time.monotonic()
            self.seq_seen = {c: now for c in self.applied_seq}


# --------------------------------------------------------------------------
# server
# --------------------------------------------------------------------------

class PsServer:
    """Hosts one shard; serves PULL/PUSH/SAVE/STATS/STOP over TCP and
    registers its endpoint in the coord store under
    `ps/<name>/server/<id>` (epoch-tagged in the registry for operator
    debugging; clients recover from restarts by re-resolving the endpoint
    on connection failure)."""

    def __init__(self, name, server_id, num_servers, embedding_dim,
                 store=None, ckpt_dir=None, optimizer="adagrad",
                 learning_rate=0.05, init_std=None, seed=0, host="127.0.0.1"):
        self.name = name
        self.server_id = int(server_id)
        self.num_servers = int(num_servers)
        self.store = store
        self.ckpt_dir = ckpt_dir
        self.shard = SparseTableShard(embedding_dim, optimizer=optimizer,
                                      learning_rate=learning_rate,
                                      init_std=init_std,
                                      seed=seed * 7919 + self.server_id)
        if ckpt_dir:
            p = self._ckpt_path()
            if os.path.exists(p):
                self.shard.load(p)     # restart-with-recovery path
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        if store is not None:
            epoch = store.add(f"ps/{name}/epoch/{server_id}", 1)
            store.set(f"ps/{name}/server/{server_id}",
                      f"{host}:{self.port}:{epoch}".encode())

    def _ckpt_path(self):
        return os.path.join(self.ckpt_dir,
                            f"{self.name}.shard{self.server_id}.pkl")

    def serve_forever(self):
        self._sock.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        self._sock.close()

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    req = _recv_msg(conn)
                except (ConnectionError, EOFError):
                    return
                op = req["op"]
                if op == "pull":
                    _send_msg(conn, {"ok": True,
                                     "rows": self.shard.pull(req["uids"])})
                elif op == "push":
                    self.shard.push(req["uids"], req["grads"],
                                    lr=req.get("lr"),
                                    client=req.get("client"),
                                    seq=req.get("seq"))
                    _send_msg(conn, {"ok": True})
                elif op == "save":
                    if not self.ckpt_dir:
                        _send_msg(conn, {"ok": False, "err": "no ckpt_dir"})
                    else:
                        self.shard.save(self._ckpt_path())
                        _send_msg(conn, {"ok": True})
                elif op == "stats":
                    _send_msg(conn, {
                        "ok": True, "server_id": self.server_id,
                        "rows": len(self.shard.rows),
                        "dim": self.shard.dim,
                        "applied_pushes": self.shard.applied_pushes})
                elif op == "stop":
                    _send_msg(conn, {"ok": True})
                    self._stop.set()
                    return
                else:
                    _send_msg(conn, {"ok": False, "err": f"bad op {op}"})
        finally:
            conn.close()


def serve_shard(name, server_id, num_servers, embedding_dim, store_port,
                ckpt_dir, **kw):
    """Process entry point: build the server, register, serve until STOP.
    (Module-level so multiprocessing can spawn it by reference.)"""
    from .store import TCPStore

    store = TCPStore("127.0.0.1", store_port)
    srv = PsServer(name, server_id, num_servers, embedding_dim, store=store,
                   ckpt_dir=ckpt_dir, **kw)
    srv.serve_forever()


# --------------------------------------------------------------------------
# client
# --------------------------------------------------------------------------

class PsClient:
    """Trainer-side client: splits requests by the mod row→server mapping,
    fans out, reassembles. On connection failure it re-resolves the
    server's endpoint from the coord store and retries with backoff —
    surviving a server kill+restart (the brpc naming-service recovery);
    retried pushes carry a (client, seq) id so the server applies each
    gradient exactly once."""

    def __init__(self, name, num_servers, store, timeout=60.0):
        import uuid

        self.name = name
        self.num_servers = int(num_servers)
        self.store = store
        self.timeout = float(timeout)
        self._conns: dict = {}
        self._client_id = uuid.uuid4().hex
        self._seq = 0
        self._dim = None  # table embedding_dim, cached from responses

    def server_of(self, uids):
        return np.asarray(uids, np.int64) % self.num_servers

    # -- connection management --------------------------------------------
    def _resolve(self, sid):
        raw = self.store.get(f"ps/{self.name}/server/{sid}").decode()
        host, port, epoch = raw.rsplit(":", 2)
        return host, int(port), int(epoch)

    def _connect(self, sid):
        host, port, _epoch = self._resolve(sid)
        s = socket.create_connection((host, port), timeout=self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._conns[sid] = s
        return s

    def _request(self, sid, req):
        deadline = time.monotonic() + self.timeout
        delay = 0.05
        while True:
            try:
                s = self._conns.get(sid) or self._connect(sid)
                _send_msg(s, req)
                resp = _recv_msg(s)
                if not resp.get("ok"):
                    raise RuntimeError(
                        f"ps server {sid}: {resp.get('err')}")
                return resp
            except (ConnectionError, OSError, socket.timeout):
                # server gone — drop the conn, re-resolve (a restarted
                # server publishes a fresh endpoint+epoch), retry
                c = self._conns.pop(sid, None)
                if c is not None:
                    try:
                        c.close()
                    except OSError:
                        pass
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"ps server {sid} unreachable for {self.timeout}s")
                time.sleep(delay)
                delay = min(delay * 2, 1.0)

    # -- table ops ---------------------------------------------------------
    def table_dim(self):
        """The table's embedding_dim, cached client-side (first learned
        from a pull response, else asked of server 0's stats) so shape
        contracts hold even for requests that touch no server."""
        if self._dim is None:
            self._dim = int(self._request(0, {"op": "stats"})["dim"])
        return self._dim

    def pull(self, uids):
        uids = np.asarray(uids, np.int64).ravel()
        owner = self.server_of(uids)
        parts = {}
        for sid in np.unique(owner):
            idx = np.nonzero(owner == sid)[0]
            resp = self._request(int(sid),
                                 {"op": "pull", "uids": uids[idx]})
            parts[int(sid)] = (idx, resp["rows"])
        if parts:
            self._dim = int(next(iter(parts.values()))[1].shape[1])
            dim = self._dim
        else:
            # empty request: keep the (0, embedding_dim) shape contract
            # instead of inferring (0, 0) from an empty response set
            dim = self.table_dim()
        out = np.empty((len(uids), dim), np.float32)
        for idx, rows in parts.values():
            out[idx] = rows
        return out

    def push(self, uids, grads, lr=None):
        uids = np.asarray(uids, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(uids), -1)
        owner = self.server_of(uids)
        self._seq += 1
        for sid in np.unique(owner):
            idx = np.nonzero(owner == sid)[0]
            self._request(int(sid), {"op": "push", "uids": uids[idx],
                                     "grads": grads[idx], "lr": lr,
                                     "client": self._client_id,
                                     "seq": self._seq})

    def save(self):
        """Checkpoint every shard (reference: PSClient::save)."""
        for sid in range(self.num_servers):
            self._request(sid, {"op": "save"})

    def stats(self):
        return [self._request(sid, {"op": "stats"})
                for sid in range(self.num_servers)]

    def stop_servers(self):
        for sid in range(self.num_servers):
            try:
                self._request(sid, {"op": "stop"})
            except (TimeoutError, RuntimeError):
                pass

    def close(self):
        for s in self._conns.values():
            try:
                s.close()
            except OSError:
                pass
        self._conns.clear()
