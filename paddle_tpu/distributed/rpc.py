"""paddle.distributed.rpc — control-plane remote procedure calls.

Reference: python/paddle/distributed/rpc/rpc.py (init_rpc, rpc_sync,
rpc_async, shutdown over a brpc agent;
paddle/fluid/distributed/rpc/rpc_agent.cc).

TPU-native stance: tensor traffic belongs to XLA collectives — RPC here
is the *control plane* (worker coordination, parameter surgery, metric
collection), matching how the reference positions it. Transport is the
native coordination store (native/coord_store.cc): each worker runs a
serve loop polling its request keys; requests/replies are pickled
(fn, args, kwargs) payloads. In a single process, calls loop back
directly — same API, zero transport.
"""
from __future__ import annotations

import pickle
import threading
import time
import traceback

from ..analysis import locks as _locks
from .env import get_rank, get_world_size, get_store

_state = {
    "initialized": False,
    "name": None,
    "workers": {},      # name -> rank
    "serve_thread": None,
    "stop": False,
    "req_seq": 0,
    "lock": _locks.new_lock("rpc.state"),
    "pending": {},      # future id -> _Future (in-flight rpc_async calls)
}


class WorkerInfo:
    def __init__(self, name, rank):
        self.name = name
        self.rank = rank

    def __repr__(self):
        return f"WorkerInfo(name={self.name!r}, rank={self.rank})"


class _Future:
    """Pending rpc result.

    Abandonment semantics: a `wait(timeout)` that times out ABANDONS the
    future — it is deregistered from the pending table immediately (no
    leak), and if the remote result arrives later it is dropped (the
    response key is still consumed off the store). An abandoned future
    never transitions to done: every subsequent `wait()` raises the same
    TimeoutError, so a timed-out call cannot be silently resurrected;
    re-issue the rpc instead."""

    def __init__(self):
        self._ev = threading.Event()
        self._lock = _locks.new_lock("rpc.future")
        self._value = None
        self._err = None
        self._abandoned = False

    def _register(self):
        with _state["lock"]:
            _state["pending"][id(self)] = self

    def _deregister(self):
        with _state["lock"]:
            _state["pending"].pop(id(self), None)

    def _set(self, value=None, err=None):
        with self._lock:
            if self._abandoned or self._ev.is_set():
                return  # late result of a timed-out/failed call: dropped
            self._value, self._err = value, err
            self._ev.set()
        self._deregister()

    def _abandon(self, reason):
        with self._lock:
            if self._ev.is_set() or self._abandoned:
                return False
            self._abandoned = True
            self._err = reason
            self._ev.set()  # wake every other waiter blocked in wait()
        self._deregister()
        return True

    def wait(self, timeout=None):
        if self._abandoned:
            raise TimeoutError(
                "rpc future was abandoned by an earlier wait() timeout — "
                "re-issue the call")
        if not self._ev.wait(timeout):
            if self._abandon(f"abandoned after wait timeout ({timeout}s)"):
                raise TimeoutError(
                    f"rpc result timed out after {timeout}s; future "
                    f"abandoned (a late result will be dropped — re-issue "
                    f"the call)")
            # lost the race: the future resolved (or was abandoned by a
            # concurrent waiter) exactly at the timeout boundary — fall
            # through so the outcome is reported for what it is
        if self._abandoned:
            raise TimeoutError(
                "rpc future was abandoned by a concurrent wait() timeout — "
                "re-issue the call")
        if self._err is not None:
            raise RuntimeError(f"rpc raised on the remote worker:\n"
                               f"{self._err}")
        return self._value

    result = wait

    def done(self):
        """True once a real result/error landed; abandoned futures never
        report done (their late result is dropped)."""
        return self._ev.is_set() and not self._abandoned


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Register this worker and start serving requests (reference:
    rpc.init_rpc)."""
    if _state["initialized"]:
        return
    rank = get_rank() if rank is None else rank
    world = get_world_size() if world_size is None else world_size
    _state["name"] = name
    # epoch-namespace all request/response/seq keys: after shutdown()+
    # init_rpc() in the same job, the fresh serve loop reads epoch-local
    # keys, so a persisted rpc/seq counter can't make callers enqueue at
    # sequence numbers the server never polls (advisor r2 finding)
    _state["epoch"] = _state.get("epoch", -1) + 1
    store = get_store()
    if store is not None and world > 1:
        ep = _state["epoch"]
        store.set(f"rpc/{ep}/worker/{rank}", name.encode())
        for r in range(world):
            other = store.wait(f"rpc/{ep}/worker/{r}").decode()
            _state["workers"][other] = r
        t = threading.Thread(target=_serve_loop, daemon=True)
        t.start()
        _state["serve_thread"] = t
    else:
        _state["workers"][name] = rank
    _state["initialized"] = True


def get_worker_info(name=None):
    if name is None:
        return WorkerInfo(_state["name"],
                          _state["workers"].get(_state["name"], 0))
    if name not in _state["workers"]:
        raise ValueError(f"unknown rpc worker {name!r}")
    return WorkerInfo(name, _state["workers"][name])


def get_all_worker_infos():
    return [WorkerInfo(n, r) for n, r in sorted(_state["workers"].items(),
                                                key=lambda kv: kv[1])]


def _open_client():
    """Dedicated store connection for an rpc thread: the native client
    handle is one socket with a request/response protocol — sharing it
    across threads interleaves frames (a blocking barrier on the main
    thread would starve the serve loop)."""
    from .store import TCPStore

    base = get_store()
    return TCPStore(base.host, base.port, world_size=base.world_size)


def _serve_loop():
    import sys

    store = _open_client()
    rank = get_rank()
    ep = _state["epoch"]
    served = 0
    while not _state["stop"]:
        key = f"rpc/{ep}/req/{rank}/{served}"
        try:
            raw = store.get_nowait(key)
        except Exception:  # tpu-lint: disable=TL007 — logged below; the
            # serve loop must outlive transient store/socket faults
            print(f"rpc serve loop (rank {rank}) store fault:\n"
                  f"{traceback.format_exc()}", file=sys.stderr)
            time.sleep(0.05)
            continue
        if raw is None:
            time.sleep(0.01)
            continue
        try:
            fn, args, kwargs = pickle.loads(raw)
            result = fn(*args, **(kwargs or {}))
            payload = pickle.dumps(("ok", result))
        except Exception:  # tpu-lint: disable=TL007 — user-fn error: the
            # full traceback is serialized back to the caller, not eaten
            payload = pickle.dumps(("err", traceback.format_exc()))
        store.set(f"rpc/{ep}/res/{rank}/{served}", payload)
        store.delete_key(key)
        served += 1
    store.close()


def rpc_async(to, fn, args=None, kwargs=None, timeout=None):
    """Reference: rpc.rpc_async — returns a Future."""
    if not _state["initialized"]:
        raise RuntimeError("call paddle.distributed.rpc.init_rpc first")
    args = args or ()
    fut = _Future()
    if to == _state["name"] or get_world_size() == 1:
        # register only once the call is definitely in flight — a failed
        # validation/enqueue below must not leak a pending entry
        fut._register()  # deregistered on completion or timeout abandon
        def run_local():
            try:
                fut._set(value=fn(*args, **(kwargs or {})))
            except Exception:  # tpu-lint: disable=TL007 — forwarded to
                fut._set(err=traceback.format_exc())  # the caller's Future
        threading.Thread(target=run_local, daemon=True).start()
        return fut

    store = get_store()
    dst = get_worker_info(to).rank
    ep = _state["epoch"]
    with _state["lock"]:
        seq_key = f"rpc/{ep}/seq/{dst}"
        seq = store.add(seq_key, 1) - 1
    store.set(f"rpc/{ep}/req/{dst}/{seq}", pickle.dumps((fn, args, kwargs)))
    fut._register()  # the request is on the wire from here on

    def wait_reply():
        try:
            conn = _open_client()  # own socket: never shares the handle
            try:
                raw = conn.wait(f"rpc/{ep}/res/{dst}/{seq}",
                                timeout=timeout)
                status, payload = pickle.loads(raw)
                conn.delete_key(f"rpc/{ep}/res/{dst}/{seq}")
            finally:
                conn.close()
            if status == "ok":
                fut._set(value=payload)
            else:
                fut._set(err=payload)
        except Exception:  # tpu-lint: disable=TL007 — forwarded to the
            fut._set(err=traceback.format_exc())  # caller's Future

    threading.Thread(target=wait_reply, daemon=True).start()
    return fut


def rpc_sync(to, fn, args=None, kwargs=None, timeout=None):
    """Reference: rpc.rpc_sync — blocking call, returns the result."""
    return rpc_async(to, fn, args=args, kwargs=kwargs,
                     timeout=timeout).wait(timeout)


class RRef:
    """Minimal remote-reference: owns a Future; to_here() fetches the
    value (reference: the RRef surface of distributed/rpc)."""

    def __init__(self, fut, owner):
        self._fut = fut
        self._owner = owner

    def to_here(self, timeout=None):
        return self._fut.wait(timeout)

    def owner(self):
        return get_worker_info(self._owner)


def remote(to, fn, args=None, kwargs=None, timeout=None):
    return RRef(rpc_async(to, fn, args=args, kwargs=kwargs,
                          timeout=timeout), to)


def shutdown(graceful=True):
    """Reference: rpc.shutdown — barrier then stop serving."""
    if not _state["initialized"]:
        return
    store = get_store()
    if graceful and store is not None and get_world_size() > 1:
        store.barrier("rpc_shutdown", world_size=get_world_size())
    _state["stop"] = True
    t = _state["serve_thread"]
    if t is not None:
        t.join(timeout=2)
    # fail any still-pending futures: their reply threads die with the
    # process-wide key space, so waiting on them would hang forever
    with _state["lock"]:
        leftover = list(_state["pending"].values())
        _state["pending"].clear()
    for f in leftover:
        f._set(err="rpc shut down before the result arrived")
    _state.update(initialized=False, name=None, serve_thread=None,
                  stop=False, workers={})
    # epoch survives the reset: the next init_rpc starts a new key space
