"""Parameter/activation sharding specs and rules.

Reference analog: the SPMD rules + TensorDistAttr machinery
(paddle/phi/infermeta/spmd_rules/, paddle/phi/core/distributed/auto_parallel/
dist_attr.h) that annotate every tensor with a placements vector. On TPU the
propagation engine is GSPMD inside XLA; our job is only to pin the *sources*:
parameter shardings (by logical-axis annotation, by layer type or by name
pattern) and batch shardings. GSPMD then inserts the collectives the
reference's reshard functions implement by hand.

Since the `paddle_tpu.sharding` subsystem landed, resolution is rule-table
driven: parameters carry *logical* axis names ("embed"/"heads"/"mlp"/
"vocab", set by mp_layers or by name-pattern rules below) and ONE
first-match-wins table (`sharding.rules`) maps them onto whatever mesh is
in use — "tp" on a MeshConfig serving mesh, "mp" on the hybrid training
topology — so every subsystem agrees on placement. Legacy physical
`dist_spec` PartitionSpecs are still honored (axes absent from the mesh
are dropped)."""
from __future__ import annotations

import re

import jax

from .. import sharding as _shardlib
from ..core.tensor import Tensor

# Megatron-style tensor-parallel rules for transformer parameter names
# (matches paddle_tpu.models.gpt naming; users can pass their own table).
# Values are LOGICAL axis tuples resolved through the sharding rule table:
# column-parallel weights shard the output dim ("heads"/"mlp"), row-parallel
# weights the input dim, vocab-parallel embeddings the vocab dim. Legacy
# tables whose values are PartitionSpecs keep working (treated as physical).
DEFAULT_TP_RULES = [
    (r".*\b(qkv_proj|q_proj|k_proj|v_proj)\.weight$", ("embed", "heads")),
    (r".*\b(qkv_proj|q_proj|k_proj|v_proj)\.bias$", ("heads",)),
    (r".*\b(gate_up_proj|up_proj|gate_proj|fc1)\.weight$", ("embed", "mlp")),
    (r".*\b(gate_up_proj|up_proj|gate_proj|fc1)\.bias$", ("mlp",)),
    (r".*\b(out_proj|o_proj)\.weight$", ("heads", "embed")),
    (r".*\b(down_proj|fc2)\.weight$", ("mlp", "embed")),
    (r".*\b(wte|embed_tokens|word_embeddings)\.weight$", ("vocab", "embed")),
    (r".*\blm_head\.weight$", ("embed", "vocab")),
]


def _is_physical(entries):
    """A rule value is physical when it is a PartitionSpec (legacy user
    tables); plain tuples/lists hold logical axis names."""
    from jax.sharding import PartitionSpec

    return isinstance(entries, PartitionSpec)


def _filter_physical(spec, mesh):
    """Drop physical axes the mesh does not have (a legacy P("mp") spec
    must resolve to replicated on a dp/fsdp/tp mesh, not error)."""
    if mesh is None:
        return spec
    sizes = dict(mesh.shape)
    return _shardlib.spec(*[
        e if e is None or all(a in sizes
                              for a in ((e,) if isinstance(e, str) else e))
        else None
        for e in spec])


def _uses_axis(entries, axis):
    return any(e == axis or (isinstance(e, tuple) and axis in e)
               for e in entries if e is not None)


def _shard_largest_free_dim(entries, shape, axis, n_shard):
    """ZeRO-style fallback shared by the stage-3 ('sharding' axis) and
    fsdp paths: shard the largest still-unsharded dim divisible by the
    axis size; a spec already using the axis (or with no divisible free
    dim) is returned unchanged — placement must never fail."""
    if _uses_axis(entries, axis):
        return entries
    cand = sorted((i for i, e in enumerate(entries) if e is None),
                  key=lambda i: -shape[i])
    for i in cand:
        if shape[i] % n_shard == 0:
            entries[i] = axis
            break
    return entries


def _fsdp_ways(mesh):
    """The mesh's fsdp degree (1 when absent): ``MeshConfig(fsdp=N)`` is
    the ONE switch that turns on fsdp-by-default resolution here — no
    per-model spec tables, no engine flag."""
    return dict(mesh.shape).get("fsdp", 1) if mesh is not None else 1


def spec_for_param(name, param, rules=None, *, sharding_stage=0,
                   mesh=None, axis_rules=None):
    """Compute the PartitionSpec for one parameter.

    Priority: `param.logical_axes` (logical annotation, set by mp_layers)
    > explicit `param.dist_spec` (physical, set by legacy layers) >
    name-pattern `rules` > replicated. Logical names resolve through the
    active axis-rule table (or `axis_rules`) against `mesh`. If
    sharding_stage == 3, additionally shard the largest still-unsharded
    dim over the 'sharding' axis (ZeRO-3 param sharding ≈
    GroupShardedStage3, group_sharded_stage3.py:85).

    A mesh carrying ``fsdp > 1`` (``MeshConfig(fsdp=N)``) selects
    fsdp-by-default resolution: logical names resolve through the
    `sharding.fsdp_rules` preset (embed-dim first, tp keeps its claim on
    the tp dims) and any parameter still fully free afterwards shards its
    largest divisible dim along ``fsdp`` — so params AND optimizer slots
    hold ~1/N per chip, gathered in-graph at use sites by GSPMD and
    reduce-scattered on the grad path (docs/sharding.md)."""
    fsdp_n = _fsdp_ways(mesh)
    if fsdp_n > 1:
        from ..sharding.rules import fsdp_rules

        # augmenting the ACTIVE table (or the caller's) keeps explicit
        # user rules winning first-match; the preset only adds candidates
        axis_rules = fsdp_rules(axis_rules)
    spec = None
    logical = getattr(param, "logical_axes", None)
    if logical is not None:
        spec = _shardlib.logical_to_spec(logical, mesh=mesh,
                                         rules=axis_rules)
    if spec is None:
        spec = getattr(param, "dist_spec", None)
        if spec is not None and not _is_physical(spec):
            spec = _shardlib.spec(*spec)
        if spec is not None:
            spec = _filter_physical(spec, mesh)
    if spec is None and rules:
        for pat, s in rules:
            if re.match(pat, name):
                if _is_physical(s):
                    spec = _filter_physical(s, mesh)
                else:
                    spec = _shardlib.logical_to_spec(s, mesh=mesh,
                                                     rules=axis_rules)
                break
    entries = list(spec) if spec is not None else [None] * param.ndim
    while len(entries) < param.ndim:
        entries.append(None)
    if mesh is not None:
        # a dim the candidate axis does not divide replicates instead of
        # failing placement (vocab=50257 on tp=8 stays whole; GSPMD still
        # shards everything else)
        from ..sharding.rules import _divisible_spec

        entries = list(_divisible_spec(
            _shardlib.spec(*entries), tuple(param.shape), mesh))
    if sharding_stage >= 3 and mesh is not None and \
            dict(mesh.shape).get("sharding", 1) > 1:
        entries = _shard_largest_free_dim(
            entries, param.shape, "sharding",
            dict(mesh.shape)["sharding"])
    if fsdp_n > 1:
        # largest-divisible-dim fallback: unannotated params (layer
        # norms, biases, position tables) still shard 1/N
        entries = _shard_largest_free_dim(entries, param.shape, "fsdp",
                                          fsdp_n)
    return _shardlib.spec(*entries)


def opt_state_spec(param_spec, param_shape, mesh, *, sharding_stage=0):
    """Sharding for per-param optimizer slots (ZeRO stage >= 1 shards them
    over the sharding axis — reference DygraphShardingOptimizer
    dygraph_sharding_optimizer.py:48 / stage2 group_sharded_optimizer_stage2
    .py:53). On an fsdp mesh the slots follow the param spec (already
    fsdp-sharded) with the same largest-divisible-dim fallback, so the
    optimizer state — 2x the params for AdamW — also holds ~1/N per
    chip."""
    entries = list(param_spec)
    while len(entries) < len(param_shape):
        entries.append(None)
    if sharding_stage >= 1 and mesh is not None and \
            dict(mesh.shape).get("sharding", 1) > 1:
        entries = _shard_largest_free_dim(
            entries, param_shape, "sharding", dict(mesh.shape)["sharding"])
    fsdp_n = _fsdp_ways(mesh)
    if fsdp_n > 1:
        entries = _shard_largest_free_dim(entries, param_shape, "fsdp",
                                          fsdp_n)
    return _shardlib.spec(*entries)


def shard_params(layer, mesh, rules=None, *, sharding_stage=0):
    """Eagerly place every parameter/buffer of `layer` on the mesh with its
    computed sharding (device_put — this is the moment memory actually
    distributes, ≈ TensorParallel wrapper broadcasting/splitting params,
    meta_parallel/tensor_parallel.py)."""
    rules = DEFAULT_TP_RULES if rules is None else rules
    specs = {}
    for name, p in layer.named_parameters():
        spec = spec_for_param(name, p, rules, sharding_stage=sharding_stage,
                              mesh=mesh)
        specs[name] = spec
        p._value = jax.device_put(p._value,
                                  _shardlib.named_sharding(mesh, spec))
    for name, b in layer.named_buffers():
        if isinstance(b, Tensor):
            b._value = jax.device_put(
                b._value, _shardlib.replicated(mesh, b.ndim))
    return specs


def shard_constraint(x, *entries):
    """with_sharding_constraint over *physical* entries, usable on eager
    Tensors inside traced code; outside a trace it's an eager device_put
    when a mesh is active (the reshard of auto_parallel/api.py:282). For
    logical names use `sharding.with_logical_constraint`."""
    from . import topology as topo_mod
    mesh = topo_mod.get_mesh()
    if mesh is None:
        return x
    sh = _shardlib.named_sharding(mesh, entries)
    if isinstance(x, Tensor):
        v = x._value
        if isinstance(v, jax.core.Tracer):
            return Tensor(jax.lax.with_sharding_constraint(v, sh))
        return Tensor(jax.device_put(v, sh))
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sh)
    return jax.device_put(x, sh)
