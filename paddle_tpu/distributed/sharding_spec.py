"""Parameter/activation sharding specs and rules.

Reference analog: the SPMD rules + TensorDistAttr machinery
(paddle/phi/infermeta/spmd_rules/, paddle/phi/core/distributed/auto_parallel/
dist_attr.h) that annotate every tensor with a placements vector. On TPU the
propagation engine is GSPMD inside XLA; our job is only to pin the *sources*:
parameter shardings (by layer type or by name pattern) and batch shardings.
GSPMD then inserts the collectives the reference's reshard functions
implement by hand.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor

# Megatron-style tensor-parallel rules for transformer parameter names
# (matches paddle_tpu.models.gpt naming; users can pass their own table).
# column-parallel: output dim sharded; row-parallel: input dim sharded;
# vocab-parallel embedding: row (vocab) dim sharded.
DEFAULT_TP_RULES = [
    (r".*\b(qkv_proj|gate_up_proj|up_proj|q_proj|k_proj|v_proj|gate_proj|fc1)\.weight$", P(None, "mp")),
    (r".*\b(qkv_proj|gate_up_proj|up_proj|q_proj|k_proj|v_proj|gate_proj|fc1)\.bias$", P("mp")),
    (r".*\b(out_proj|down_proj|o_proj|fc2)\.weight$", P("mp", None)),
    (r".*\b(wte|embed_tokens|word_embeddings)\.weight$", P("mp", None)),
    (r".*\blm_head\.weight$", P(None, "mp")),
]


def spec_for_param(name, param, rules=None, *, sharding_stage=0,
                   mesh=None):
    """Compute the NamedSharding spec for one parameter.

    Priority: explicit `param.dist_spec` (set by mp_layers) > name-pattern
    rules > replicated. If sharding_stage == 3, additionally shard the
    largest still-unsharded dim over the 'sharding' axis (ZeRO-3 param
    sharding ≈ GroupShardedStage3, group_sharded_stage3.py:85)."""
    spec = getattr(param, "dist_spec", None)
    if spec is None and rules:
        for pat, s in rules:
            if re.match(pat, name):
                spec = s
                break
    entries = list(spec) if spec is not None else [None] * param.ndim
    while len(entries) < param.ndim:
        entries.append(None)
    if sharding_stage >= 3 and mesh is not None and mesh.shape.get("sharding", 1) > 1:
        n_shard = mesh.shape["sharding"]
        # biggest free dim divisible by the axis size
        cand = sorted(
            (i for i, e in enumerate(entries) if e is None),
            key=lambda i: -param.shape[i])
        for i in cand:
            if param.shape[i] % n_shard == 0:
                entries[i] = "sharding"
                break
    return P(*entries)


def opt_state_spec(param_spec, param_shape, mesh, *, sharding_stage=0):
    """Sharding for per-param optimizer slots (ZeRO stage >= 1 shards them
    over the sharding axis — reference DygraphShardingOptimizer
    dygraph_sharding_optimizer.py:48 / stage2 group_sharded_optimizer_stage2
    .py:53)."""
    entries = list(param_spec)
    while len(entries) < len(param_shape):
        entries.append(None)
    if sharding_stage >= 1 and mesh is not None and mesh.shape.get("sharding", 1) > 1:
        n_shard = mesh.shape["sharding"]
        if not any(e == "sharding" or (isinstance(e, tuple) and "sharding" in e)
                   for e in entries):
            cand = sorted(
                (i for i, e in enumerate(entries) if e is None),
                key=lambda i: -param_shape[i])
            for i in cand:
                if param_shape[i] % n_shard == 0:
                    entries[i] = "sharding"
                    break
    return P(*entries)


def shard_params(layer, mesh, rules=None, *, sharding_stage=0):
    """Eagerly place every parameter/buffer of `layer` on the mesh with its
    computed sharding (device_put — this is the moment memory actually
    distributes, ≈ TensorParallel wrapper broadcasting/splitting params,
    meta_parallel/tensor_parallel.py)."""
    rules = DEFAULT_TP_RULES if rules is None else rules
    specs = {}
    for name, p in layer.named_parameters():
        spec = spec_for_param(name, p, rules, sharding_stage=sharding_stage,
                              mesh=mesh)
        specs[name] = spec
        p._value = jax.device_put(p._value, NamedSharding(mesh, spec))
    for name, b in layer.named_buffers():
        if isinstance(b, Tensor):
            b._value = jax.device_put(
                b._value, NamedSharding(mesh, P(*([None] * b.ndim))))
    return specs


def shard_constraint(x, *entries):
    """with_sharding_constraint usable on eager Tensors inside traced code;
    outside a trace it's an eager device_put when a mesh is active (the
    reshard of auto_parallel/api.py:282)."""
    from . import topology as topo_mod
    mesh = topo_mod.get_mesh()
    if mesh is None:
        return x
    spec = P(*entries)
    if isinstance(x, Tensor):
        v = x._value
        if isinstance(v, jax.core.Tracer):
            return Tensor(jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, spec)))
        return Tensor(jax.device_put(v, NamedSharding(mesh, spec)))
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.device_put(x, NamedSharding(mesh, spec))
