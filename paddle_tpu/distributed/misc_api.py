"""Distributed surface completions (reference: the tail of
python/paddle/distributed/__init__.py — alltoall_single, dist.split,
shard_optimizer, DistModel/Strategy/to_static, PS dataset configs,
backend introspection, gloo CPU barrier trio).
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import collective as C
from .env import get_rank, get_world_size, get_store

__all__ = [
    "alltoall", "alltoall_single", "scatter_object_list", "wait",
    "get_backend", "is_available", "destroy_process_group",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
    "ReduceType", "DistAttr", "split", "shard_optimizer",
    "unshard_dtensor", "Strategy", "DistModel", "to_static",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry",
]


# -- comm tail ---------------------------------------------------------------

def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Alias surface of collective.all_to_all (reference keeps both)."""
    return C.all_to_all(out_tensor_list, in_tensor_list, group=group,
                        sync_op=sync_op)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Reference: communication/all_to_all.py alltoall_single — exchange
    contiguous dim0 blocks of ONE tensor across ranks.

    Controller semantics match the other dense collectives: a value
    actually sharded over the group axis exchanges blocks via the compiled
    lax.all_to_all; a replicated value is the world-of-one arithmetic
    no-op (every rank holds identical data, so the exchange returns the
    same tensor)."""
    if group is None:
        group = C.new_group(axis="dp")
    v = in_tensor._value if isinstance(in_tensor, Tensor) \
        else jnp.asarray(in_tensor)
    if group.nranks > 1 and C._axis_sharded(v, group.mesh, group.axis):
        from ..compat import shard_map
        spec = v.sharding.spec

        def body(x):
            return jax.lax.all_to_all(x, group.axis, split_axis=0,
                                      concat_axis=0, tiled=True)

        fn = shard_map(body, mesh=group.mesh, in_specs=(spec,),
                       out_specs=spec, check_vma=False)
        res = jax.jit(fn)(v)
    else:
        res = v
    if isinstance(out_tensor, Tensor):
        out_tensor._value = res
        return out_tensor
    return Tensor(res)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Reference: communication/scatter.py scatter_object_list."""
    import pickle
    world, rank = get_world_size(), get_rank()
    if world == 1:
        out_object_list[:] = [in_object_list[0]] if in_object_list else []
        return
    store = get_store()
    if store is None:
        raise RuntimeError("scatter_object_list needs a launched job store")
    from .p2p import _obj_seq
    seq = _obj_seq["scatter_obj"]
    _obj_seq["scatter_obj"] += 1
    if rank == src:
        for r in range(world):
            store.set(f"obj/scatter/{seq}/{r}",
                      pickle.dumps(in_object_list[r]))
    mine = pickle.loads(store.wait(f"obj/scatter/{seq}/{rank}"))
    out_object_list[:] = [mine]
    done = store.add(f"obj/scatter/{seq}/done", 1)
    if done == world:
        for r in range(world):
            store.delete_key(f"obj/scatter/{seq}/{r}")
        store.delete_key(f"obj/scatter/{seq}/done")


def wait(tensor, group=None, use_calc_stream=True):
    """Reference: communication/wait.py — fence a collective's result.
    Host readback is the only reliable fence through a PJRT relay."""
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    np.asarray(jax.device_get(jax.tree_util.tree_leaves(v)[0]))
    return tensor


def get_backend(group=None):
    """Reference: get_backend returns 'NCCL'/'GLOO'; the comm backend here
    is XLA's compiled collectives over ICI/DCN."""
    return "XLA"


def is_available():
    """Reference: distributed.is_available."""
    return True


def destroy_process_group(group=None):
    """Reference: destroy_process_group — tear down comm state. Drops the
    process-global HCG (compiled collectives hold no persistent comms)."""
    from . import topology as topo_mod
    topo_mod.set_hybrid_communicate_group(None)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Reference: CPU-only gloo bootstrap trio. The native coordination
    store plays gloo's role here."""
    import os
    os.environ.setdefault("PADDLE_TPU_PROCESS_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TPU_NUM_PROCESSES", str(rank_num))
    os.environ.setdefault("PADDLE_TPU_COORDINATOR", server_endpoint)
    from .env import init_parallel_env
    init_parallel_env()


def gloo_barrier():
    C.barrier()


def gloo_release():
    destroy_process_group()


class ReduceType:
    """Reference: auto_parallel ReduceType for Partial placements."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4


class DistAttr:
    """Reference: DistAttr(mesh, placements) — static-graph dist attr."""

    def __init__(self, mesh, placements):
        self.process_mesh = mesh
        self.placements = placements

    def __repr__(self):
        return f"DistAttr(mesh={self.process_mesh}, " \
               f"placements={self.placements})"


# -- TP split / dtensor tail -------------------------------------------------

def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference: distributed/parallel.py split — build a model-parallel
    linear/embedding whose weight is partitioned across the mp axis.
    Mesh-native: the weight is shard_tensor'd over 'mp'; GSPMD inserts the
    partial-sum all-reduce (linear) or gather (embedding)."""
    import paddle_tpu as paddle
    from . import topology as topo_mod

    hcg = topo_mod.get_hybrid_communicate_group()
    mesh = hcg.mesh if hcg is not None else None
    if operation == "linear":
        in_f, out_f = size
        w = paddle.randn([in_f, out_f]) * (1.0 / np.sqrt(in_f))
        if mesh is not None and mesh.shape.get("mp", 1) > 1:
            from ..sharding import named_sharding, spec as spec_of
            sp = spec_of(None, "mp") if axis == 1 else spec_of("mp", None)
            w._value = jax.device_put(w._value, named_sharding(mesh, sp))
        return paddle.matmul(x, w)
    if operation == "embedding":
        vocab, dim = size
        w = paddle.randn([vocab, dim]) * 0.02
        if mesh is not None and mesh.shape.get("mp", 1) > 1:
            from ..sharding import named_sharding, spec as spec_of
            w._value = jax.device_put(
                w._value, named_sharding(mesh, spec_of("mp", None)))
        from ..nn.functional import embedding
        return embedding(x, w)
    raise ValueError(f"split: unknown operation {operation!r}")


def shard_optimizer(optimizer, shard_fn=None):
    """Reference: auto_parallel/api.py shard_optimizer — optimizer states
    follow their parameters' shardings. States here are created by the
    engine with the param's sharding already; this wraps step() to apply
    shard_fn to newly created state tensors."""
    if shard_fn is None:
        return optimizer
    orig_step = optimizer.step

    def step(*a, **k):
        out = orig_step(*a, **k)
        for attr, val in vars(optimizer).items():
            if isinstance(val, dict):
                for key, st in val.items():
                    if isinstance(st, Tensor):
                        val[key] = shard_fn(key, None, st)
        return out

    optimizer.step = step
    return optimizer


def unshard_dtensor(dist_tensor):
    """Reference: auto_parallel/api.py unshard_dtensor — gather a
    sharded tensor to a fully replicated one."""
    v = dist_tensor._value if isinstance(dist_tensor, Tensor) \
        else dist_tensor
    sh = getattr(v, "sharding", None)
    if sh is not None and hasattr(sh, "mesh"):
        from ..sharding import replicated
        v = jax.device_put(v, replicated(sh.mesh))
    return Tensor(v)


# -- auto-parallel static API (DistModel / Strategy / to_static) ------------

class Strategy:
    """Reference: auto_parallel/strategy.py Strategy — config bundle the
    static Engine consumes (sharding/amp/recompute/pipeline sub-configs)."""

    class _Cfg:
        def __init__(self, **kw):
            self.__dict__.update(kw)

    def __init__(self, config=None):
        cfg = config or {}
        self.sharding = Strategy._Cfg(enable=False, degree=1, stage=1,
                                      **cfg.get("sharding", {}))
        self.amp = Strategy._Cfg(enable=False, dtype="bfloat16",
                                 **cfg.get("amp", {}))
        self.recompute = Strategy._Cfg(enable=False,
                                       **cfg.get("recompute", {}))
        self.pipeline = Strategy._Cfg(enable=False, schedule_mode="1F1B",
                                      micro_batch_size=1,
                                      **cfg.get("pipeline", {}))


class DistModel:
    """Reference: auto_parallel/api.py DistModel — the trainable object
    dist.to_static returns: __call__ runs one step in the current mode
    (train/eval/predict) on the sharded program."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None):
        from .engine import parallelize
        from . import topology as topo_mod
        self._layer = layer
        self._loss = loss
        self._strategy = strategy or Strategy()
        hcg = topo_mod.get_hybrid_communicate_group()
        mesh = hcg.mesh if hcg is not None else None
        stage = (self._strategy.sharding.stage
                 if self._strategy.sharding.enable else 0)
        loss_fn = None
        if loss is not None:
            def loss_fn(m, *batch):
                out = m(*batch[:-1])
                return loss(out, batch[-1])
        self._step = parallelize(
            layer, optimizer, loss_fn=loss_fn, mesh=mesh,
            sharding_stage=2 if stage >= 2 else 0,
            compute_dtype=(self._strategy.amp.dtype
                           if self._strategy.amp.enable else None))
        self._mode = "train"

    def train(self):
        self._mode = "train"
        self._layer.train()

    def eval(self):
        self._mode = "eval"
        self._layer.eval()

    def predict(self):
        self._mode = "predict"
        self._layer.eval()

    def state_dict(self, *a, **k):
        return self._layer.state_dict(*a, **k)

    def dist_main_program(self, mode=None):
        return self._step          # the compiled step IS the program

    def __call__(self, *batch):
        if self._mode == "train":
            return self._step.train_batch(*batch)
        from ..core.dispatch import no_grad
        with no_grad():
            if self._mode == "eval" and self._loss is not None:
                out = self._layer(*batch[:-1])
                return self._loss(out, batch[-1])
            return self._layer(*batch)


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None):
    """Reference: dist.to_static (auto_parallel/api.py) — lift a dygraph
    layer + loss + optimizer into a DistModel over the current mesh."""
    return DistModel(layer, loader=loader, loss=loss, optimizer=optimizer,
                     strategy=strategy)


# -- PS dataset configs ------------------------------------------------------

class CountFilterEntry:
    """Reference: distributed/entry_attr.py CountFilterEntry — admit a
    sparse feature into the table only after `count` shows (maps onto the
    host table's eviction/liveness counters)."""

    def __init__(self, count):
        if count < 0:
            raise ValueError("count must be >= 0")
        self.count = int(count)

    def _to_attr(self):
        return f"count_filter_entry:{self.count}"


class ProbabilityEntry:
    """Reference: entry_attr.py ProbabilityEntry — admit with probability."""

    def __init__(self, probability):
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        self.probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self.probability}"


class ShowClickEntry:
    """Reference: entry_attr.py ShowClickEntry — show/click slot names for
    CTR accessors."""

    def __init__(self, show_slot, click_slot):
        self.show_slot = str(show_slot)
        self.click_slot = str(click_slot)

    def _to_attr(self):
        return f"show_click_entry:{self.show_slot}:{self.click_slot}"


class InMemoryDataset:
    """Reference: distributed/fleet/dataset InMemoryDataset (C++ DataFeed
    ingest). Python-native: slot-record text files load into memory, then
    iterate as (slot_1 ids, ..., label) batches through paddle.io.

    Line format (the reference's slot data feed): whitespace-separated
    `slot:id` tokens plus an optional `label:x` token."""

    def __init__(self):
        self._records = []
        self._filelist = []
        self._slots = []
        self._batch_size = 1

    def init(self, batch_size=1, use_var=None, pipe_command=None,
             **kwargs):
        self._batch_size = int(batch_size)
        self._slots = [getattr(v, "name", str(i))
                       for i, v in enumerate(use_var or [])]

    set_batch_size = init

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def load_into_memory(self):
        self._records = []
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    rec = {}
                    for tok in line.split():
                        k, _, v = tok.partition(":")
                        rec.setdefault(k, []).append(float(v)
                                                     if k == "label"
                                                     else int(v))
                    if rec:
                        self._records.append(rec)

    def local_shuffle(self):
        import random
        random.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num=1):
        self.local_shuffle()

    def release_memory(self):
        self._records = []

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def __len__(self):
        return len(self._records)

    def __iter__(self):
        slots = self._slots or sorted(
            {k for r in self._records for k in r if k != "label"})
        for r in self._records:
            feats = [np.asarray(r.get(s, [0]), np.int64) for s in slots]
            yield tuple(feats) + (np.float32(r.get("label", [0.0])[0]),)


class QueueDataset(InMemoryDataset):
    """Reference: QueueDataset — streaming variant; here the same reader
    without the in-memory shuffle contract."""

    def load_into_memory(self):  # streaming: files read lazily
        pass

    def __iter__(self):
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    rec = {}
                    for tok in line.split():
                        k, _, v = tok.partition(":")
                        rec.setdefault(k, []).append(float(v)
                                                     if k == "label"
                                                     else int(v))
                    if not rec:
                        continue
                    slots = self._slots or sorted(
                        k for k in rec if k != "label")
                    feats = [np.asarray(rec.get(s, [0]), np.int64)
                             for s in slots]
                    yield tuple(feats) + (np.float32(
                        rec.get("label", [0.0])[0]),)
