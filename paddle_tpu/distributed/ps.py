"""Parameter-server workload answer: mesh-sharded embedding training.

Reference analog: the brpc parameter server (fluid/distributed/ps/ —
BrpcPsServer/Client, memory_sparse_table, TheOnePSRuntime the_one_ps.py:
1028) that search/rec workloads use to hold 100B-feature embedding tables
with async sparse push/pull.

TPU-native redesign: there are no parameter servers — the mesh IS the
parameter server. Embedding tables shard their rows across ALL devices
(P over the flattened mesh axes), lookups compile to gathers whose
cross-chip traffic rides ICI (XLA inserts the collective), and "sparse
push" is the scatter-add cotangent of the gather inside the same jitted
train step — synchronous, exact, and overlap-scheduled by the compiler
instead of an async brpc pipeline. Capacity scales with pod HBM
(reference tables scale with host DRAM); the CPU/host tier of the
reference (ssd_sparse_table) maps to host-offloaded tables via
jax.device_put with host memory kinds when needed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn.layer.layers import Layer
from .. import nn

__all__ = ["ShardedEmbedding", "DistributedLookupTable"]


class ShardedEmbedding(Layer):
    """Embedding with rows sharded over mesh axes (default: every axis —
    the whole pod holds one table, like a PS fleet holds one table).

    Use under `distributed.parallelize`: the row dim carries the sharding
    spec; XLA turns the id gather into (gather + collective) on ICI.
    sparse_grad parity: the backward is a scatter-add into the sharded
    rows — only touched rows produce traffic, the SelectedRows analog.
    """

    def __init__(self, num_embeddings, embedding_dim, axes=("mp",),
                 sparse=True, weight_attr=None, scale_grad_by_freq=False):
        super().__init__()
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        std = 1.0 / max(1.0, np.sqrt(embedding_dim))
        self.weight = self.create_parameter(
            [self.num_embeddings, self.embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.Normal(0.0, std))
        # row-sharded over the given mesh axes (tuple spec shards the row
        # dim over their product)
        self.weight.dist_spec = P(tuple(axes), None)

    def forward(self, ids):
        return apply("sharded_embedding", _lookup_impl,
                     [self.weight, ids], {})


def _lookup_impl(table, ids):
    return jnp.take(table, ids, axis=0)


class DistributedLookupTable(Layer):
    """Multi-slot lookup (reference: the PS pull_sparse over slots +
    fused embedding): one shared table, a list of id slots, concatenated
    slot embeddings out — the rec-model front end."""

    def __init__(self, num_embeddings, embedding_dim, num_slots,
                 axes=("mp",)):
        super().__init__()
        self.embedding = ShardedEmbedding(num_embeddings, embedding_dim,
                                          axes=axes)
        self.num_slots = int(num_slots)

    def forward(self, slot_ids):
        """slot_ids: [batch, num_slots] int -> [batch, num_slots*dim]."""
        emb = self.embedding(slot_ids)  # [b, slots, dim]
        return emb.reshape([emb.shape[0], -1])
