"""Parameter-server workload answer: mesh-sharded embedding training.

Reference analog: the brpc parameter server (fluid/distributed/ps/ —
BrpcPsServer/Client, memory_sparse_table, TheOnePSRuntime the_one_ps.py:
1028) that search/rec workloads use to hold 100B-feature embedding tables
with async sparse push/pull.

TPU-native redesign: there are no parameter servers — the mesh IS the
parameter server. Embedding tables shard their rows across ALL devices
(P over the flattened mesh axes), lookups compile to gathers whose
cross-chip traffic rides ICI (XLA inserts the collective), and "sparse
push" is the scatter-add cotangent of the gather inside the same jitted
train step — synchronous, exact, and overlap-scheduled by the compiler
instead of an async brpc pipeline. Capacity scales with pod HBM
(reference tables scale with host DRAM); the CPU/host tier of the
reference (ssd_sparse_table) maps to host-offloaded tables via
jax.device_put with host memory kinds when needed.
"""
from __future__ import annotations

import collections
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..nn.layer.layers import Layer
from ..sharding import named_sharding as _named_sharding, spec as _pspec
from .. import nn

__all__ = ["ShardedEmbedding", "DistributedLookupTable",
           "HostOffloadedEmbedding"]


class ShardedEmbedding(Layer):
    """Embedding with rows sharded over mesh axes (default: every axis —
    the whole pod holds one table, like a PS fleet holds one table).

    Use under `distributed.parallelize`: the row dim carries the sharding
    spec; XLA turns the id gather into (gather + collective) on ICI.
    sparse_grad parity: the backward is a scatter-add into the sharded
    rows — only touched rows produce traffic, the SelectedRows analog.
    """

    def __init__(self, num_embeddings, embedding_dim, axes=("mp",),
                 sparse=True, weight_attr=None, scale_grad_by_freq=False):
        super().__init__()
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        std = 1.0 / max(1.0, np.sqrt(embedding_dim))
        self.weight = self.create_parameter(
            [self.num_embeddings, self.embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.Normal(0.0, std))
        # row-sharded over the given mesh axes (tuple spec shards the row
        # dim over their product)
        self.weight.dist_spec = _pspec(tuple(axes), None)

    def forward(self, ids):
        return apply("sharded_embedding", _lookup_impl,
                     [self.weight, ids], {})


def _lookup_impl(table, ids):
    return jnp.take(table, ids, axis=0)


class AsyncPushCommunicator:
    """Background sparse-push worker with bounded staleness (reference:
    fluid/distributed/ps/service/communicator/communicator.h AsyncCommunicator
    — trainer threads enqueue gradient segments, send threads merge and push,
    `max_merge_var_num`/queue size bound the staleness window).

    TPU-native shape: the dense step (compiled, on-chip) never waits for the
    host-table scatter; pushes ride a queue drained by one worker thread.
    The staleness bound is `max_pending` outstanding pushes — when the queue
    is full the trainer blocks, so a row can be at most `max_pending` pushes
    stale when read. flush() is the barrier (checkpointing, eval)."""

    def __init__(self, apply_fn, max_pending=8):
        self._apply = apply_fn
        self.max_pending = int(max_pending)
        self._q = collections.deque()
        self._cv = threading.Condition()
        self._busy = False
        self._stop = False
        self.pushed = 0          # applied by the worker
        self.enqueued = 0
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def put(self, uids, row_ct):
        with self._cv:
            while len(self._q) >= self.max_pending:   # staleness bound
                self._cv.wait()
            self._q.append((uids, row_ct))
            self.enqueued += 1
            self._cv.notify_all()

    def _loop(self):
        from .. import profiler as _prof
        while True:
            with self._cv:
                while not self._q and not self._stop:
                    self._cv.wait()
                if self._stop and not self._q:
                    return
                uids, row_ct = self._q.popleft()
                self._busy = True
                self._cv.notify_all()
            try:
                with _prof.RecordEvent("ps_async_push"):
                    self._apply(uids, row_ct)
            finally:
                with self._cv:
                    self._busy = False
                    self.pushed += 1
                    self._cv.notify_all()
                from ..core import monitor
                monitor.increment("ps_async_push_total")

    def flush(self):
        """Barrier: wait until every enqueued push has been applied."""
        with self._cv:
            while self._q or self._busy:
                self._cv.wait()

    @property
    def pending(self):
        with self._cv:
            return len(self._q) + (1 if self._busy else 0)

    def shutdown(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout=5)


class HostOffloadedEmbedding(Layer):
    """Embedding table resident in HOST memory with sparse on-table updates
    and an optional HBM hot-row cache.

    Reference analog: the PS host/SSD table tier —
    paddle/fluid/distributed/ps/table/memory_sparse_table.cc +
    ssd_sparse_table.h, whose capacity is host DRAM/SSD (not accelerator
    memory) and whose optimizer (sgd/adagrad accessors,
    table/sparse_sgd_rule.cc) lives WITH the table, applying per-row
    sparse pushes.

    TPU-native redesign:
    - the table array is placed with the `pinned_host` memory kind (jax
      memories API); lookups compile to a host-space gather of the
      *deduplicated* ids followed by one host->HBM transfer of just the
      touched rows — HBM never holds the table or a dense gradient;
    - the backward pass delivers row cotangents to the table's own sparse
      optimizer (sgd or adagrad), which scatter-updates the host rows in
      place (donated buffer) — the analog of the PS async sparse push,
      made synchronous and compiled;
    - `cache_size` > 0 keeps an LRU cache of hot rows in device memory
      for eval/predict flows (valid because eval never mutates rows).

    The table is NOT a dense Parameter: framework optimizers skip it, the
    table optimizes itself (exactly the reference PS contract where the
    worker optimizer never sees sparse tables).
    """

    def __init__(self, num_embeddings, embedding_dim, optimizer="adagrad",
                 learning_rate=0.05, initializer_range=None, axes=None,
                 cache_size=0, dtype=jnp.float32, async_push=False,
                 max_pending=8):
        super().__init__()
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.optimizer = optimizer
        self.learning_rate = float(learning_rate)
        self.cache_size = int(cache_size)
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError("optimizer must be 'sgd' or 'adagrad'")

        std = (initializer_range if initializer_range is not None
               else 1.0 / max(1.0, np.sqrt(embedding_dim)))
        init = np.random.normal(
            0.0, std, (self.num_embeddings, self.embedding_dim)).astype(
                np.dtype(dtype))
        self._host_sharding, self._dev_sharding = self._shardings(axes)
        table = jax.device_put(init, self._host_sharding)
        self.weight = Tensor(table, stop_gradient=True)
        if optimizer == "adagrad":
            self._accum = jax.device_put(
                np.zeros((self.num_embeddings,), np.float32),
                self._acc_host_sharding)
        else:
            self._accum = None
        # LRU cache state (eval only): id -> slot, plus the HBM row store
        self._cache_rows = None
        self._cache_map = {}
        self._cache_clock = []
        self._push_probe = None
        # async communicator (reference communicator.h semantics)
        self._lock = threading.Lock()
        self._comm = AsyncPushCommunicator(
            self._apply_push_sync, max_pending) if async_push else None
        # per-row liveness for the eviction/TTL story (reference
        # memory_sparse_table shrink): step counter + last-touched step
        self._step = 0
        self._last_seen = np.zeros((self.num_embeddings,), np.int64)

    def _shardings(self, axes):
        from . import topology as topo_mod
        from ..compat import supports_memory_kind

        # backends without distinct host/device memory spaces (older jax
        # CPU) degrade gracefully: the table stays in default memory,
        # which IS host memory there
        def _kind(sh, kind):
            return sh.with_memory_kind(kind) \
                if supports_memory_kind(kind) else sh

        hcg = topo_mod.get_hybrid_communicate_group()
        if axes and hcg is not None:
            mesh = hcg.mesh
            host = _kind(_named_sharding(mesh, (tuple(axes), None)),
                         "pinned_host")
            dev = _kind(_named_sharding(mesh, ()), "device")
            self._acc_host_sharding = _kind(
                _named_sharding(mesh, (tuple(axes),)), "pinned_host")
        else:
            d = jax.devices()[0]
            host = _kind(jax.sharding.SingleDeviceSharding(d),
                         "pinned_host")
            dev = _kind(jax.sharding.SingleDeviceSharding(d), "device")
            self._acc_host_sharding = host
        return host, dev

    # -- compiled host-space kernels ------------------------------------
    def _pull_fn(self):
        host, dev = self._host_sharding, self._dev_sharding

        def pull(table, uids):
            uh = jax.device_put(uids, host)
            rows = table.at[uh].get(mode="promise_in_bounds")
            return jax.device_put(rows, dev)

        return jax.jit(pull)

    def _push_fn(self):
        """Compiled host-space scatter update (the TPU path: table rows
        update IN host memory, only cotangents transit HBM)."""
        host = self._host_sharding
        acc_host = self._acc_host_sharding
        opt = self.optimizer

        def push(table, accum, uids, ct, lr):
            # pads duplicate a live id with ZERO cotangent, so every write
            # must be scatter-ADD (duplicate .set has an unspecified winner
            # and can drop the real update)
            uh = jax.device_put(uids, host)
            ct_h = jax.device_put(ct, host)
            lr_h = jax.device_put(lr, host)
            if opt == "adagrad":
                g2 = jnp.sum(ct_h * ct_h, axis=-1)
                accum = accum.at[uh].add(g2, mode="promise_in_bounds")
                acc_rows = accum.at[uh].get(mode="promise_in_bounds")
                scale = (lr_h / jnp.sqrt(acc_rows + 1e-10))[:, None]
            else:
                scale = lr_h
            table = table.at[uh].add(-scale * ct_h,
                                     mode="promise_in_bounds")
            return table, accum

        return jax.jit(push, donate_argnums=(0, 1),
                       out_shardings=(host, acc_host))

    def _host_push_works(self):
        """Probe once whether XLA can execute host-space scatter on this
        backend (TPU: yes; CPU runtime lacks the Host
        annotate_device_placement custom call)."""
        if self._push_probe is None:
            try:
                probe_tab = jax.device_put(
                    np.zeros((2, self.embedding_dim), np.float32),
                    self._host_sharding)
                probe_acc = jax.device_put(np.zeros((2,), np.float32),
                                           self._acc_host_sharding)
                t, a = self._push(probe_tab, probe_acc,
                                  jnp.zeros((1,), jnp.int32),
                                  jnp.zeros((1, self.embedding_dim)),
                                  jnp.float32(0.0))
                jax.block_until_ready(t)
                self._push_probe = True
            except Exception:  # tpu-lint: disable=TL007 — capability
                # probe: ANY failure means "no device push path here"
                self._push_probe = False
        return self._push_probe

    def _numpy_push(self, uids, row_ct):
        """Fallback sparse push: row updates via a host->host numpy pass.
        Capacity-equivalent (the table never touches device memory); the
        full-table host memcpy it costs is what the compiled host-space
        path above removes on TPU."""
        tab = np.array(self.weight._value)
        ids = np.asarray(uids)
        ct = np.asarray(row_ct, tab.dtype)
        if self.optimizer == "adagrad":
            acc = np.array(self._accum)
            g2 = np.sum(np.asarray(row_ct, np.float32) ** 2, axis=-1)
            np.add.at(acc, ids, g2)  # add-per-occurrence: pads add zero
            scale = (self.learning_rate
                     / np.sqrt(acc[ids] + 1e-10))[:, None]
            self._accum = jax.device_put(acc, self._acc_host_sharding)
        else:
            scale = self.learning_rate
        np.subtract.at(tab, ids, (scale * ct).astype(tab.dtype))
        self.weight._value = jax.device_put(tab, self._host_sharding)

    def forward(self, ids):
        flat = ids._value.reshape(-1) if isinstance(ids, Tensor) \
            else jnp.asarray(ids).reshape(-1)
        orig_shape = tuple(ids.shape)
        if not self.training and self.cache_size > 0:
            rows = self._cached_lookup(np.asarray(flat))
            out = rows.reshape(orig_shape + (self.embedding_dim,))
            return Tensor(out)
        # real host-side dedup (the forward is eager, so dynamic-size unique
        # is fine); pad the unique set to the next power of two so the pull/
        # push jits see a bounded set of shapes instead of one per count
        uids_np, inv_np = np.unique(np.asarray(flat), return_inverse=True)
        n_u = len(uids_np)
        padded = 1 << (n_u - 1).bit_length() if n_u > 1 else 1
        uids_np = np.concatenate(
            [uids_np, np.full(padded - n_u, uids_np[0], uids_np.dtype)])
        uids = jnp.asarray(uids_np.astype(np.int32))
        inv = jnp.asarray(inv_np.astype(np.int32))
        if not hasattr(self, "_pull"):
            self._pull = self._pull_fn()
            self._push = self._push_fn()
        with self._lock:
            table_ref = self.weight._value   # consistent snapshot vs worker
        rows_u = self._pull(table_ref, uids)
        rows = rows_u[inv].reshape(orig_shape + (self.embedding_dim,))
        out = Tensor(rows, stop_gradient=not self.training)
        if self.training:
            out._grad_node = _SparsePushNode(self, uids, inv, orig_shape)
            out._out_idx = 0
        return out

    def _apply_push(self, uids, row_ct):
        """Sparse push entry. Sync mode applies inline; async mode enqueues
        and returns — the dense step proceeds while the worker thread
        scatters into the host table (bounded staleness)."""
        self._step += 1
        self._last_seen[np.asarray(uids)] = self._step
        if self._comm is not None:
            self._comm.put(uids, row_ct)
            return
        self._apply_push_sync(uids, row_ct)

    def flush(self):
        """Drain pending async pushes (call before eval/checkpoint)."""
        if self._comm is not None:
            self._comm.flush()

    def evict_stale(self, max_age):
        """TTL eviction (reference: memory_sparse_table.cc shrink / SSD
        tier demotion): rows untouched for `max_age` pushes are reset to
        fresh init values and their optimizer state cleared — bounding the
        effective hot set the way the reference bounds table growth."""
        self.flush()
        with self._lock:
            stale = np.nonzero((self._step - self._last_seen)
                               > int(max_age))[0]
            if len(stale) == 0:
                return 0
            tab = np.array(self.weight._value)
            std = 1.0 / max(1.0, np.sqrt(self.embedding_dim))
            tab[stale] = np.random.normal(
                0.0, std, (len(stale), self.embedding_dim)).astype(tab.dtype)
            self.weight._value = jax.device_put(tab, self._host_sharding)
            if self._accum is not None:
                acc = np.array(self._accum)
                acc[stale] = 0.0
                self._accum = jax.device_put(acc, self._acc_host_sharding)
            self._cache_map.clear()
            self._cache_clock.clear()
            return int(len(stale))

    def _apply_push_sync(self, uids, row_ct):
        """Sparse push: table's own optimizer updates touched rows."""
        with self._lock:
            self._apply_push_locked(uids, row_ct)

    def _apply_push_locked(self, uids, row_ct):
        if self._host_push_works():
            acc = self._accum if self._accum is not None else \
                jax.device_put(np.zeros((1,), np.float32),
                               self._acc_host_sharding)
            new_table, new_acc = self._push(
                self.weight._value, acc, uids, row_ct,
                jnp.float32(self.learning_rate))
            self.weight._value = new_table
            if self._accum is not None:
                self._accum = new_acc
        else:
            self._numpy_push(uids, row_ct)
        self._cache_map.clear()  # rows changed: invalidate the HBM cache
        self._cache_clock.clear()

    # -- eval-time HBM hot-row cache ------------------------------------
    def _cached_lookup(self, flat_np):
        if self._cache_rows is None:
            self._cache_rows = jnp.zeros(
                (self.cache_size, self.embedding_dim),
                self.weight._value.dtype)
        uniq = np.unique(flat_np)
        if len(uniq) > self.cache_size:
            # working set exceeds the cache: serve this batch directly from
            # the host table, leave the cache untouched
            if not hasattr(self, "_pull"):
                self._pull = self._pull_fn()
                self._push = self._push_fn()
            return self._pull(self.weight._value,
                              jnp.asarray(flat_np, jnp.int32))
        # LRU-touch this batch's hits FIRST so the miss-fill below can never
        # evict a row the same batch still needs
        for rid in uniq:
            rid = int(rid)
            if rid in self._cache_map:
                self._cache_clock.remove(rid)
                self._cache_clock.append(rid)
        missing = [int(i) for i in uniq if int(i) not in self._cache_map]
        if missing:
            if not hasattr(self, "_pull"):
                self._pull = self._pull_fn()
                self._push = self._push_fn()
            rows = self._pull(self.weight._value,
                              jnp.asarray(missing, jnp.int32))
            for k, rid in enumerate(missing):
                if len(self._cache_map) >= self.cache_size:
                    evict = self._cache_clock.pop(0)
                    slot = self._cache_map.pop(evict)
                else:
                    slot = len(self._cache_map)
                self._cache_map[rid] = slot
                self._cache_clock.append(rid)
                self._cache_rows = self._cache_rows.at[slot].set(rows[k])
        slots = np.asarray([self._cache_map[int(i)] for i in flat_np],
                           np.int32)
        return self._cache_rows[jnp.asarray(slots)]

    @property
    def memory_kind(self):
        return self.weight._value.sharding.memory_kind


class _SparsePushNode:
    """Tape node delivering row cotangents to the table's sparse optimizer
    (the PS 'push_sparse' analog, fluid/distributed/ps/service/
    brpc_ps_client.cc push_sparse)."""

    def __init__(self, table, uids, inv, ids_shape):
        from ..core.dispatch import GradNode
        self.name = "host_table_push"
        self.impl = None
        self.statics = {}
        self.statics_key = ()
        self.input_arrays = []
        self.input_metas = []
        self.n_outputs = 1
        self.out_is_seq = False
        self._table = table
        self._uids = uids
        self._inv = inv
        self._ids_shape = ids_shape
        GradNode._counter[0] += 1
        self._id = GradNode._counter[0]

    def run_vjp_taped(self, cotangents):
        # push_sparse is a side effect (host-table optimizer apply), not a
        # differentiable op; under create_graph the push still happens and
        # no second-order graph exists past the table (input_metas is []).
        from ..core.tensor import Tensor
        return self.run_vjp(
            [c._value if isinstance(c, Tensor) else c for c in cotangents])

    def run_vjp(self, cotangents):
        ct = cotangents[0]
        dim = self._table.embedding_dim
        flat_ct = ct.reshape(-1, dim)
        # fold duplicate ids: segment-sum cotangents onto unique rows
        row_ct = jax.ops.segment_sum(
            flat_ct, self._inv, num_segments=self._uids.shape[0])
        self._table._apply_push(self._uids, row_ct)
        return []

    def release(self):
        pass


class DistributedLookupTable(Layer):
    """Multi-slot lookup (reference: the PS pull_sparse over slots +
    fused embedding): one shared table, a list of id slots, concatenated
    slot embeddings out — the rec-model front end."""

    def __init__(self, num_embeddings, embedding_dim, num_slots,
                 axes=("mp",)):
        super().__init__()
        self.embedding = ShardedEmbedding(num_embeddings, embedding_dim,
                                          axes=axes)
        self.num_slots = int(num_slots)

    def forward(self, slot_ids):
        """slot_ids: [batch, num_slots] int -> [batch, num_slots*dim]."""
        emb = self.embedding(slot_ids)  # [b, slots, dim]
        return emb.reshape([emb.shape[0], -1])


# ---------------------------------------------------------------------------
# CTR accessor + cross-process PS service (round 4)
# ---------------------------------------------------------------------------


class CtrAccessorConfig:
    """Reference: the ctr_accessor_param proto consumed by
    paddle/fluid/distributed/ps/table/ctr_accessor.cc:37."""

    def __init__(self, nonclk_coeff=0.1, click_coeff=1.0,
                 show_click_decay_rate=0.98, delete_threshold=0.8,
                 delete_after_unseen_days=30, embedx_threshold=10.0):
        self.nonclk_coeff = float(nonclk_coeff)
        self.click_coeff = float(click_coeff)
        self.show_click_decay_rate = float(show_click_decay_rate)
        self.delete_threshold = float(delete_threshold)
        self.delete_after_unseen_days = float(delete_after_unseen_days)
        self.embedx_threshold = float(embedx_threshold)


class CtrAccessor:
    """Per-feature CTR scoring/lifecycle (reference:
    ps/table/ctr_accessor.h:30, .cc — CtrCommonFeatureValue carries
    show/click/unseen_days; Shrink() time-decays then deletes by score;
    NeedExtendMF() gates the wide embedx vector on the same score).

    TPU-native: the accessor is a numpy-side policy object attached to a
    host table — scoring math matches the reference exactly; storage stays
    columnar (dict of arrays) instead of packed float rows."""

    def __init__(self, config=None):
        self.cfg = config or CtrAccessorConfig()
        self.show = {}          # uid -> float
        self.click = {}
        self.unseen_days = {}

    def show_click_score(self, show, click):
        """Reference ctr_accessor.cc:305: (show-click)*nonclk + click*clk."""
        c = self.cfg
        return (show - click) * c.nonclk_coeff + click * c.click_coeff

    def update(self, uids, shows, clicks):
        """Push-side stat fold (CtrCommonPushValue merge): accumulate
        show/click and reset unseen_days for the touched rows. Aging is a
        separate daily pass (age_days) like the reference — doing it per
        push would both cost O(table) per batch and count batches as
        days."""
        for u, s, k in zip(np.asarray(uids).tolist(),
                           np.asarray(shows).tolist(),
                           np.asarray(clicks).tolist()):
            self.show[u] = self.show.get(u, 0.0) + float(s)
            self.click[u] = self.click.get(u, 0.0) + float(k)
            self.unseen_days[u] = 0.0

    def age_days(self, days=1.0):
        """Daily aging pass (reference: unseen_days accrues per day and is
        consumed by Shrink)."""
        for u in self.show:
            self.unseen_days[u] = self.unseen_days.get(u, 0.0) + days

    def score(self, uid):
        return self.show_click_score(self.show.get(uid, 0.0),
                                     self.click.get(uid, 0.0))

    def need_extend_mf(self, uid):
        """Reference ctr_accessor.cc:190 NeedExtendMF: grow the wide
        embedx vector only once the feature's score crosses the
        threshold."""
        return self.score(uid) >= self.cfg.embedx_threshold

    def shrink(self):
        """Reference ctr_accessor.cc:62 Shrink: decay show/click first,
        then delete rows whose score fell below delete_threshold or that
        were unseen too long. Returns the deleted uids."""
        c = self.cfg
        dead = []
        for u in list(self.show):
            self.show[u] *= c.show_click_decay_rate
            self.click[u] *= c.show_click_decay_rate
            if (self.show_click_score(self.show[u], self.click[u])
                    < c.delete_threshold
                    or self.unseen_days.get(u, 0.0)
                    > c.delete_after_unseen_days):
                dead.append(u)
                self.show.pop(u, None)
                self.click.pop(u, None)
                self.unseen_days.pop(u, None)
        return dead


# -- cross-process push: workers send sparse grads to the owner process ----

_PS_TABLES: dict = {}
# rpc's SAME-PROCESS fast path runs each call on its own thread; pushes
# must serialize like the cross-process serve loop does naturally
_PS_LOCK = threading.Lock()


def host_ps_table(name, table, accessor=None):
    """Owner-process side: register a HostOffloadedEmbedding (or any object
    with _apply_push(uids, row_ct)) under `name` so remote workers can push
    to it via dist.rpc (reference: the brpc PsService hosting tables,
    ps/service/brpc_ps_server.h)."""
    _PS_TABLES[name] = (table, accessor)
    return table


def _ps_remote_push(name, uids, row_ct, shows=None, clicks=None):
    """Runs in the OWNER process via rpc: apply a sparse push (and CTR
    stats when provided). Module-level so rpc can pickle the reference."""
    with _PS_LOCK:
        table, accessor = _PS_TABLES[name]
        table._apply_push(jnp.asarray(np.asarray(uids)),
                          jnp.asarray(np.asarray(row_ct)))
        if accessor is not None and shows is not None:
            accessor.update(uids, clicks=clicks, shows=shows)
    return True


def _ps_remote_pull(name, uids):
    table, _ = _PS_TABLES[name]
    rows = np.asarray(table.weight._value)[np.asarray(uids)]
    return rows


class RemoteCommunicator:
    """Worker-process side: async sparse push to the owner's table over
    dist.rpc with bounded staleness (reference: the cross-node
    AsyncCommunicator, ps/service/communicator/communicator.h:427 — send
    queues bounded by max_merge/independent thread; here jax/numpy grads
    ride the native-store rpc channel and at most `max_pending` pushes may
    be in flight before the caller blocks)."""

    def __init__(self, owner, table_name, max_pending=8):
        self.owner = owner
        self.table_name = table_name
        self.max_pending = int(max_pending)
        self._futs = []

    def push(self, uids, row_ct, shows=None, clicks=None):
        from . import rpc as _rpc
        while len(self._futs) >= self.max_pending:
            self._futs.pop(0).wait(timeout=120)
        fut = _rpc.rpc_async(
            self.owner, _ps_remote_push,
            args=(self.table_name, np.asarray(uids),
                  np.asarray(row_ct),
                  None if shows is None else np.asarray(shows),
                  None if clicks is None else np.asarray(clicks)))
        self._futs.append(fut)
        return fut

    def pull(self, uids):
        from . import rpc as _rpc
        return _rpc.rpc_sync(self.owner, _ps_remote_pull,
                             args=(self.table_name, np.asarray(uids)),
                             timeout=120)

    def flush(self):
        while self._futs:
            self._futs.pop(0).wait(timeout=120)

    @property
    def pending(self):
        self._futs = [f for f in self._futs if not f.done()]
        return len(self._futs)
