"""Pipeline-ready GPT: stacked (scan-over-layers) parameters.

Reference analog: GPTForPretrainingPipe-style models built from
`PipelineLayer` LayerDesc lists (fleet/meta_parallel/pp_layers.py:237).

TPU-native redesign: instead of materializing one module per layer and
partitioning modules across ranks, ALL transformer blocks share one set of
parameter arrays with a leading layer dim [L, ...]:
- pp=1: the forward is a `lax.scan` over L — O(1) compile time in depth.
- pp>1: the leading dim is sharded over the 'pp' mesh axis and the forward
  runs the compiled GPipe rotation (distributed.pipeline.spmd_pipeline)
  with `ppermute` hops on the ICI ring.
- Tensor-parallel composes: the per-layer weight dims carry 'mp' specs.
Embeddings / final norm / tied lm-head live outside the pipelined region,
replicated over pp (sharded over mp), exactly like the reference's shared
embedding layers (SharedLayerDesc pp_layers.py:76).

The whole loss is ONE tape op in eager mode and traces cleanly under the
parallel engine.
"""
from __future__ import annotations

import math
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..sharding import spec as _pspec
from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..distributed import topology as topo_mod
from ..distributed.pipeline import (spmd_pipeline, spmd_pipeline_1f1b,
                                    microbatch, unmicrobatch)
from .gpt import GPTConfig, CONFIGS


def _block_fn(x, lp, *, num_heads, eps):
    """One pre-LN transformer block over per-layer params lp (dict of
    arrays WITHOUT the layer dim)."""
    b, s, h = x.shape
    hd = h // num_heads

    def ln(v, w, bias):
        mu = v.mean(-1, keepdims=True)
        var = ((v - mu) ** 2).mean(-1, keepdims=True)
        return (v - mu) * jax.lax.rsqrt(var + eps) * w + bias

    y = ln(x, lp["ln1_w"], lp["ln1_b"])
    qkv = y @ lp["qkv_w"] + lp["qkv_b"]
    # [Q|K|V] block layout — same as gpt.py's qkv_proj, so checkpoints can
    # move between the per-layer and stacked models
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(b, s, num_heads, hd)
    k = k.reshape(b, s, num_heads, hd)
    v = v.reshape(b, s, num_heads, hd)
    att = jax.nn.dot_product_attention(q, k, v, is_causal=True)
    x = x + att.reshape(b, s, h) @ lp["out_w"] + lp["out_b"]
    y = ln(x, lp["ln2_w"], lp["ln2_b"])
    y = jax.nn.gelu(y @ lp["up_w"] + lp["up_b"], approximate=True)
    x = x + y @ lp["down_w"] + lp["down_b"]
    return x


def _loss_head(lnf_w, lnf_b, wte, y, labels, *, eps, vocab_size):
    """Final LN + tied-logit next-token CE — the single loss head shared by
    the serial, GPipe-tail and 1F1B (per-microbatch) paths."""
    mu = y.mean(-1, keepdims=True)
    var = ((y - mu) ** 2).mean(-1, keepdims=True)
    h = (y - mu) * jax.lax.rsqrt(var + eps) * lnf_w + lnf_b
    logits = (h @ wte.T)[:, :-1].reshape(-1, vocab_size)
    tgt = labels[:, 1:].reshape(-1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(logp, tgt[:, None], axis=-1).mean()


def _stage_fn(stage_params, x, *, num_heads, eps):
    """Run this stage's K stacked layers (leading dim) via scan."""

    def body(carry, lp):
        return _block_fn(carry, lp, num_heads=num_heads, eps=eps), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


class GPTForCausalLMPipe(nn.Layer):
    """Stacked-parameter causal LM; pipeline-parallel when mesh pp > 1."""

    def __init__(self, cfg: GPTConfig, num_microbatches=1,
                 pipeline_schedule="gpipe", num_virtual_stages=1):
        """pipeline_schedule: 'gpipe' (fill-drain scan, AD backward; with
        num_virtual_stages>1 the circular/interleaved VPP variant,
        reference pipeline_parallel.py:906) or '1f1b' (single-program
        interleaved forward/backward with bounded activation memory,
        reference forward_backward_pipeline pipeline_parallel.py:440)."""
        super().__init__()
        if pipeline_schedule == "1f1b" and num_virtual_stages != 1:
            raise ValueError(
                "num_virtual_stages > 1 (interleaved) is only supported "
                "with pipeline_schedule='gpipe' (circular schedule)")
        self.cfg = cfg
        self.num_microbatches = num_microbatches
        self.pipeline_schedule = pipeline_schedule
        self.num_virtual_stages = num_virtual_stages
        std = cfg.initializer_range
        L, H, V = cfg.num_layers, cfg.hidden_size, cfg.vocab_size
        I = cfg.intermediate_size

        def mk(shape, scale, spec):
            p = self.create_parameter(
                list(shape),
                default_initializer=nn.initializer.Normal(0.0, scale))
            p.dist_spec = _pspec(*spec)
            return p

        self.wte = mk((V, H), std, ("mp", None))
        self.wpe = mk((cfg.max_position_embeddings, H), std, (None, None))
        # stacked block params — layer dim first, sharded over pp
        pp = "pp"
        self.qkv_w = mk((L, H, 3 * H), std, (pp, None, "mp"))
        self.qkv_b = mk((L, 3 * H), 0.0, (pp, "mp"))
        self.out_w = mk((L, H, H), std / math.sqrt(2 * L), (pp, "mp", None))
        self.out_b = mk((L, H), 0.0, (pp, None))
        self.up_w = mk((L, H, I), std, (pp, None, "mp"))
        self.up_b = mk((L, I), 0.0, (pp, "mp"))
        self.down_w = mk((L, I, H), std / math.sqrt(2 * L), (pp, "mp", None))
        self.down_b = mk((L, H), 0.0, (pp, None))
        self.ln1_w = mk((L, H), 0.0, (pp, None))
        self.ln1_w._value = jnp.ones((L, H), jnp.float32)
        self.ln1_b = mk((L, H), 0.0, (pp, None))
        self.ln2_w = mk((L, H), 0.0, (pp, None))
        self.ln2_w._value = jnp.ones((L, H), jnp.float32)
        self.ln2_b = mk((L, H), 0.0, (pp, None))
        self.lnf_w = mk((H,), 0.0, (None,))
        self.lnf_w._value = jnp.ones((H,), jnp.float32)
        self.lnf_b = mk((H,), 0.0, (None,))

        self._stack_names = ["qkv_w", "qkv_b", "out_w", "out_b", "up_w",
                             "up_b", "down_w", "down_b", "ln1_w", "ln1_b",
                             "ln2_w", "ln2_b"]
        # stable bound-method reference: the dispatch jit cache is keyed by
        # callable identity, and `self._impl` would mint a fresh bound method
        # (→ recompile) on every access
        self._impl_fn = self._impl

    def _impl(self, ids, labels, wte, wpe, lnf_w, lnf_b, *stack,
              num_microbatches=1, mesh=None, schedule="gpipe",
              num_virtual=1):
        cfg = self.cfg
        stack_params = dict(zip(self._stack_names, stack))
        b, s = ids.shape
        x = wte[ids] + wpe[:s][None]
        stage = partial(_stage_fn, num_heads=cfg.num_heads,
                        eps=cfg.layer_norm_epsilon)
        if (mesh is not None and mesh.shape.get("pp", 1) > 1
                and schedule == "1f1b"):
            # loss head runs on the last stage inside the 1F1B program,
            # per microbatch
            def head_fn(hp, y, lbl):
                lnf_w_, lnf_b_, wte_ = hp
                return _loss_head(lnf_w_, lnf_b_, wte_, y, lbl,
                                  eps=cfg.layer_norm_epsilon,
                                  vocab_size=cfg.vocab_size)

            return spmd_pipeline_1f1b(
                stage, head_fn, stack_params, (lnf_w, lnf_b, wte),
                microbatch(x, num_microbatches),
                microbatch(labels, num_microbatches), mesh=mesh)
        if mesh is not None and mesh.shape.get("pp", 1) > 1:
            xs = microbatch(x, num_microbatches)
            out = spmd_pipeline(stage, stack_params, xs, mesh=mesh,
                                num_virtual=num_virtual)
            x = unmicrobatch(out)
        else:
            x = _stage_fn(stack_params, x,
                          num_heads=cfg.num_heads,
                          eps=cfg.layer_norm_epsilon)
        return _loss_head(lnf_w, lnf_b, wte, x, labels,
                          eps=cfg.layer_norm_epsilon,
                          vocab_size=cfg.vocab_size)

    def loss(self, input_ids, labels=None):
        if labels is None:
            labels = input_ids
        mesh = topo_mod.get_mesh()
        args = [input_ids, labels, self.wte, self.wpe, self.lnf_w, self.lnf_b]
        args += [getattr(self, n) for n in self._stack_names]
        return apply("gpt_pipe_loss", self._impl_fn, args,
                     {"num_microbatches": self.num_microbatches,
                      "mesh": mesh, "schedule": self.pipeline_schedule,
                      "num_virtual": self.num_virtual_stages})

    def forward(self, input_ids):
        return self.loss(input_ids)


def gpt_pipe(name="gpt_tiny", num_microbatches=1, pipeline_schedule="gpipe",
             num_virtual_stages=1, **overrides):
    d = dict(CONFIGS[name])
    d.update(overrides)
    return GPTForCausalLMPipe(GPTConfig(**d),
                              num_microbatches=num_microbatches,
                              pipeline_schedule=pipeline_schedule,
                              num_virtual_stages=num_virtual_stages)
