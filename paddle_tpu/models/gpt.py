"""Flagship decoder-only transformer LM (GPT/ERNIE/LLaMA-class).

Reference analogs: PaddleNLP GPT/LLaMA model zoo driven by the reference's
nn stack (python/paddle/nn/layer/transformer.py provides the generic
blocks; the fused path uses phi fusion kernels, e.g.
paddle/phi/kernels/fusion/gpu/flash_attn_kernel.cu and fused rope).

TPU-native design:
- One dense MXU-friendly stack: big [hidden, 3*hidden] fused QKV matmuls,
  bf16-ready, static shapes, no data-dependent control flow — the whole
  forward traces to a single XLA program.
- Parameter names follow a stable `layers.<i>.<block>.<w>` scheme so the
  distributed engine (paddle_tpu.distributed) can apply Megatron-style
  tensor-parallel sharding rules by name pattern (column-shard qkv/mlp-in,
  row-shard proj/mlp-out, vocab-shard embedding).
- Rotary or learned positions; pre-LN; GELU or SwiGLU MLP — covers the
  GPT-3-1.3B and LLaMA-2 configs of BASELINE.md (configs 4, 5).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np
import jax.numpy as jnp

from .. import nn
from .. import ops
from ..core.dispatch import apply
from ..nn import functional as F


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: int = 0            # 0 → = num_heads (MHA); >0 → GQA
    intermediate_size: int = 0       # 0 → 4*hidden (gelu) or 8/3*hidden (swiglu)
    max_position_embeddings: int = 1024
    rope: bool = False               # rotary (LLaMA) vs learned positions (GPT)
    rope_theta: float = 10000.0
    swiglu: bool = False             # LLaMA MLP
    rms_norm: bool = False           # LLaMA norm
    tie_word_embeddings: bool = True
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    dtype: str = "float32"

    def __post_init__(self):
        if self.num_kv_heads == 0:
            self.num_kv_heads = self.num_heads
        if self.intermediate_size == 0:
            if self.swiglu:
                # LLaMA sizing: 2/3 * 4h rounded to multiple of 128 (lane width)
                self.intermediate_size = int(
                    128 * math.ceil(8 * self.hidden_size / 3 / 128))
            else:
                self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads


# Named configs matching BASELINE.md workloads.
CONFIGS = {
    # test-size
    "gpt_tiny": dict(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, max_position_embeddings=128),
    # ERNIE-3.0-base / BERT-base class decoder (north-star tokens/sec shape)
    "gpt_base": dict(vocab_size=50304, hidden_size=768, num_layers=12,
                     num_heads=12, max_position_embeddings=1024),
    # BASELINE config 4: GPT-3 1.3B
    "gpt3_1p3b": dict(vocab_size=50304, hidden_size=2048, num_layers=24,
                      num_heads=32, max_position_embeddings=2048),
    # BASELINE config 5: LLaMA-2-7B
    "llama2_7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32,
                      num_heads=32, intermediate_size=11008,
                      max_position_embeddings=4096, rope=True, swiglu=True,
                      rms_norm=True, tie_word_embeddings=False),
}


class CacheQuantError(ValueError):
    """Unknown KV-cache quantization mode. Subclasses ValueError so
    pre-existing `except ValueError` callers keep working; raised (never
    silently ignored) for any unrecognized `quant=` argument or
    `cache_quant` attribute."""


#: spellings that mean "no quantization — plain parameter-dtype cache"
#: ("bf16" is the documented name of the unquantized layout, so an
#: explicit quant="bf16" OVERRIDES a model-level cache_quant attribute)
_NO_QUANT = (None, "", "none", "bf16")


def _normal_attr(std):
    return nn.ParamAttr(initializer=nn.initializer.Normal(0.0, std))


def _make_norm(cfg):
    if cfg.rms_norm:
        return nn.RMSNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)
    return nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_epsilon)


class GPTAttention(nn.Layer):
    """Fused-QKV causal self-attention (flash-attention path)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        h, hd = cfg.hidden_size, cfg.head_dim
        q_out = cfg.num_heads * hd
        kv_out = cfg.num_kv_heads * hd
        std = cfg.initializer_range
        bias = not cfg.rms_norm  # LLaMA-style stacks drop biases
        self.qkv_proj = nn.Linear(h, q_out + 2 * kv_out,
                                  weight_attr=_normal_attr(std),
                                  bias_attr=None if bias else False)
        self.out_proj = nn.Linear(q_out, h,
                                  weight_attr=_normal_attr(
                                      std / math.sqrt(2 * cfg.num_layers)),
                                  bias_attr=None if bias else False)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x, position_ids=None, cache=None):
        cfg = self.cfg
        b = x.shape[0]
        s = x.shape[1]
        hd = cfg.head_dim
        qkv = self.qkv_proj(x)
        q_sz = cfg.num_heads * hd
        kv_sz = cfg.num_kv_heads * hd
        q, k, v = ops.split(qkv, [q_sz, kv_sz, kv_sz], axis=-1)
        q = ops.reshape(q, [b, s, cfg.num_heads, hd])
        k = ops.reshape(k, [b, s, cfg.num_kv_heads, hd])
        v = ops.reshape(v, [b, s, cfg.num_kv_heads, hd])
        if cfg.rope:
            q, k = F.apply_rotary_pos_emb(q, k, position_ids,
                                          theta=cfg.rope_theta)
        if cache is not None:
            # KV-cached decode (reference: the cached inference path of the
            # LLM families): write this chunk's K/V at `pos`, attend over
            # the whole static-length cache with a position mask.
            # A 5-tuple cache entry is the int8-quantized layout
            # (kq, k_scale, vq, v_scale, pos) — see init_cache(quant=).
            if len(cache) == 5:
                kq_c, ks_c, vq_c, vs_c, pos = cache
                out, nkq, nks, nvq, nvs = apply(
                    "cached_attn_int8", _cached_attn_int8_impl,
                    [q, k, v, kq_c, ks_c, vq_c, vs_c, pos],
                    {"num_heads": cfg.num_heads})
                out = ops.reshape(out, [b, s, q_sz])
                return self.out_proj(out), (nkq, nks, nvq, nvs)
            k_cache, v_cache, pos = cache
            out, new_k, new_v = apply(
                "cached_attn", _cached_attn_impl,
                [q, k, v, k_cache, v_cache, pos],
                {"num_heads": cfg.num_heads})
            out = ops.reshape(out, [b, s, q_sz])
            return self.out_proj(out), (new_k, new_v)
        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            k = ops.repeat_interleave(k, rep, axis=2)
            v = ops.repeat_interleave(v, rep, axis=2)
        out, _ = F.flash_attention(q, k, v, dropout=cfg.dropout, causal=True,
                                   training=self.training)
        out = ops.reshape(out, [b, s, q_sz])
        return self.dropout(self.out_proj(out))


def _cached_attn_core(q, kk, vv, pos, num_heads, k_scale=None,
                      v_scale=None):
    """Shared cached-attention core: GQA repeat, causal mask over global
    positions, softmax, PV. Optional per-(position, head) scales fold into
    score/prob space (the int8-cache path)."""
    import jax

    hkv = kk.shape[2]
    if hkv != num_heads:
        rep = num_heads // hkv
        kk = jnp.repeat(kk, rep, axis=2)
        vv = jnp.repeat(vv, rep, axis=2)
        if k_scale is not None:
            k_scale = jnp.repeat(k_scale, rep, axis=2)
            v_scale = jnp.repeat(v_scale, rep, axis=2)
    s, t = q.shape[1], kk.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kk).astype(jnp.float32)
    if k_scale is not None:   # [B,T,H] -> [B,H,1,T]
        scores = scores * jnp.transpose(k_scale, (0, 2, 1))[:, :, None, :]
    scores = scores * scale
    q_idx = pos + jnp.arange(s)[:, None]
    mask = jnp.arange(t)[None, :] <= q_idx  # causal over global positions
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:   # fold into [B,H,q,T] probs before PV
        probs = probs * jnp.transpose(v_scale, (0, 2, 1))[:, :, None, :]
    probs = probs.astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, vv)


def _cached_attn_impl(q, k_new, v_new, k_cache, v_cache, pos, *, num_heads):
    """q [B,s,H,D]; k/v_new [B,s,Hkv,D]; caches [B,T,Hkv,D]; pos scalar
    global offset of this chunk. Returns (out, new_k_cache, new_v_cache)."""
    import jax

    k_cache = jax.lax.dynamic_update_slice_in_dim(
        k_cache, k_new.astype(k_cache.dtype), pos, axis=1)
    v_cache = jax.lax.dynamic_update_slice_in_dim(
        v_cache, v_new.astype(v_cache.dtype), pos, axis=1)
    out = _cached_attn_core(q, k_cache, v_cache, pos, num_heads)
    return out, k_cache, v_cache


def _quant_kv(x):
    """Per-(batch, position, head) symmetric int8: scale = amax/127 over
    the head dim (decode accuracy workhorse; reference analog: the LLM
    cachekv int8 path of the PaddleNLP inference stack)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale[..., 0]


def _cached_attn_int8_impl(q, k_new, v_new, kq_c, ks_c, vq_c, vs_c, pos, *,
                           num_heads):
    """int8 KV cache decode: caches store int8 values + f32 per-position
    scales ([B,T,Hkv,D] int8 + [B,T,Hkv] f32 — half the decode-loop HBM
    read of a bf16 cache). New K/V are quantized at write; the dequant
    multiply fuses into the attention matmul's operand read."""
    import jax

    knq, kns = _quant_kv(k_new)
    vnq, vns = _quant_kv(v_new)
    kq_c = jax.lax.dynamic_update_slice_in_dim(kq_c, knq, pos, axis=1)
    ks_c = jax.lax.dynamic_update_slice_in_dim(
        ks_c, kns.astype(ks_c.dtype), pos, axis=1)
    vq_c = jax.lax.dynamic_update_slice_in_dim(vq_c, vnq, pos, axis=1)
    vs_c = jax.lax.dynamic_update_slice_in_dim(
        vs_c, vns.astype(vs_c.dtype), pos, axis=1)

    # Scales fold into SCORE space ([B,H,q,T] — tiny at decode q=1) rather
    # than dequantizing the cache: a broadcast-multiply dequant would
    # materialize a full bf16 cache copy every step (measured SLOWER than
    # a bf16 cache, docs/decode_perf.md round-4 addendum).
    out = _cached_attn_core(q, kq_c.astype(q.dtype), vq_c.astype(q.dtype),
                            pos, num_heads, k_scale=ks_c, v_scale=vs_c)
    return out, kq_c, ks_c, vq_c, vs_c


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h, m = cfg.hidden_size, cfg.intermediate_size
        std = cfg.initializer_range
        bias = not cfg.rms_norm
        self.swiglu = cfg.swiglu
        if cfg.swiglu:
            # fused gate+up as one column-shardable matmul
            self.gate_up_proj = nn.Linear(h, 2 * m,
                                          weight_attr=_normal_attr(std),
                                          bias_attr=False)
        else:
            self.up_proj = nn.Linear(h, m, weight_attr=_normal_attr(std),
                                     bias_attr=None if bias else False)
        self.down_proj = nn.Linear(m, h,
                                   weight_attr=_normal_attr(
                                       std / math.sqrt(2 * cfg.num_layers)),
                                   bias_attr=None if bias else False)
        self.dropout = nn.Dropout(cfg.dropout)

    def forward(self, x):
        if self.swiglu:
            gu = self.gate_up_proj(x)
            gate, up = ops.chunk(gu, 2, axis=-1)
            x = F.silu(gate) * up
        else:
            x = F.gelu(self.up_proj(x), approximate=True)
        return self.dropout(self.down_proj(x))


class GPTBlock(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.ln_1 = _make_norm(cfg)
        self.attn = GPTAttention(cfg)
        self.ln_2 = _make_norm(cfg)
        self.mlp = GPTMLP(cfg)

    def forward(self, x, position_ids=None, cache=None):
        if cache is not None:
            att, new_cache = self.attn(self.ln_1(x), position_ids, cache)
            x = x + att
            x = x + self.mlp(self.ln_2(x))
            return x, new_cache
        x = x + self.attn(self.ln_1(x), position_ids)
        x = x + self.mlp(self.ln_2(x))
        return x


class GPTModel(nn.Layer):
    """Decoder-only LM trunk: embeddings + N blocks + final norm."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        std = cfg.initializer_range
        self.wte = nn.Embedding(cfg.vocab_size, cfg.hidden_size,
                                weight_attr=_normal_attr(std))
        if not cfg.rope:
            self.wpe = nn.Embedding(cfg.max_position_embeddings,
                                    cfg.hidden_size,
                                    weight_attr=_normal_attr(std))
        self.drop = nn.Dropout(cfg.dropout)
        self.layers = nn.LayerList([GPTBlock(cfg)
                                    for _ in range(cfg.num_layers)])
        self.ln_f = _make_norm(cfg)

    def forward(self, input_ids, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = ops.expand(
                ops.unsqueeze(ops.arange(s, dtype="int32"), 0),
                [input_ids.shape[0], s])
        x = self.wte(input_ids)
        if not self.cfg.rope:
            x = x + self.wpe(position_ids)
        x = self.drop(x)
        for blk in self.layers:
            x = blk(x, position_ids)
        return self.ln_f(x)

    def forward_step(self, input_ids, caches, pos):
        """Cached decode: input_ids [B, s] at global positions
        [pos, pos+s); caches = [(k, v)] per layer, [B, T, Hkv, D].
        Returns (hidden, new_caches)."""
        b, s = input_ids.shape
        position_ids = ops.unsqueeze(
            ops.arange(s, dtype="int32"), 0) + pos
        position_ids = ops.expand(position_ids, [b, s])
        x = self.wte(input_ids)
        if not self.cfg.rope:
            x = x + self.wpe(position_ids)
        new_caches = []
        for blk, entry in zip(self.layers, caches):
            # entry: (k, v) bf16 cache or (kq, ks, vq, vs) int8 cache
            x, nc = blk(x, position_ids, cache=(*entry, pos))
            new_caches.append(nc)
        return self.ln_f(x), new_caches


class GPTForCausalLM(nn.Layer):
    """LM head on the trunk; `forward` returns logits, `loss` the next-token
    cross entropy (labels shifted internally)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.transformer = GPTModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     weight_attr=_normal_attr(
                                         cfg.initializer_range),
                                     bias_attr=False)

    def _project(self, hidden):
        """Vocab projection (tied embedding transpose or separate head)."""
        if self.lm_head is None:
            return ops.matmul(hidden, self.transformer.wte.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, position_ids=None):
        return self._project(self.transformer(input_ids, position_ids))

    def _resolve_cache_quant(self, quant):
        """Resolve the KV-cache quantization mode with a documented
        precedence: an explicit `quant=` ARGUMENT always wins over the
        model-level `cache_quant` attribute; only `quant=None` falls back
        to the attribute (so `generate()` and the decode engine pick up a
        model-wide default without API changes, while a caller can still
        force the bf16 layout with `quant="bf16"` on a model whose
        attribute says int8). Returns None (unquantized) or "int8";
        anything else raises `CacheQuantError` — an unknown spelling must
        never silently fall back to the bf16 layout."""
        if quant is None:
            quant = getattr(self, "cache_quant", None)
        key = quant.lower() if isinstance(quant, str) else quant
        if key in _NO_QUANT:
            return None
        if key == "int8":
            return "int8"
        raise CacheQuantError(
            f"unsupported cache quant {quant!r} (supported: 'int8', or "
            f"'bf16'/None for the unquantized layout)")

    def init_cache(self, batch_size, max_length, dtype=None, quant=None):
        """Zeroed per-layer KV caches [B, T, Hkv, D] for cached decode.
        Cache dtype follows the parameters (bf16 params -> bf16 cache:
        the KV read is the decode bandwidth bill).

        quant="int8" stores int8 values plus f32 per-position scales —
        half the per-token cache read (docs/decode_perf.md names the KV
        read as the biggest weight-independent term in the decode
        floor). Precedence: the `quant=` argument wins; `quant=None`
        falls back to the model's `cache_quant` attribute (so
        `generate()` picks it up without API changes) and `quant="bf16"`
        forces the unquantized layout even then. Unknown modes raise
        `CacheQuantError` (a ValueError). For the paged layout used by
        the continuous-batching decode engine, see `init_block_pool`."""
        cfg = self.cfg
        quant = self._resolve_cache_quant(quant)
        if dtype is None:
            dtype = self.transformer.wte.weight.dtype
        shape = (batch_size, int(max_length), cfg.num_kv_heads, cfg.head_dim)
        from ..core.tensor import Tensor

        if quant == "int8":
            sshape = shape[:-1]
            return [(Tensor(jnp.zeros(shape, jnp.int8)),
                     Tensor(jnp.zeros(sshape, jnp.float32)),
                     Tensor(jnp.zeros(shape, jnp.int8)),
                     Tensor(jnp.zeros(sshape, jnp.float32)))
                    for _ in range(cfg.num_layers)]
        return [(Tensor(jnp.zeros(shape, dtype)),
                 Tensor(jnp.zeros(shape, dtype)))
                for _ in range(cfg.num_layers)]

    def init_block_pool(self, num_blocks, block_size, dtype=None,
                        quant=None, name=None):
        """Paged twin of `init_cache`: a `BlockKVCache` whose per-layer
        pool tensors use exactly this model's cache-entry order and
        dtypes — `(k, v)` blocks of the parameter dtype, or int8
        `(kq, ks, vq, vs)` quads ([N, bs, Hkv, D] int8 values +
        [N, bs, Hkv] f32 scales). Quant precedence and error semantics
        are shared with `init_cache` (`_resolve_cache_quant`). The
        continuous-batching `DecodeEngine` calls this so cache geometry
        is owned by the model, not the scheduler; with speculative
        decoding on, the engine calls it on BOTH the target and the
        draft model (`name` tags whose pool is whose — each model owns
        its own layer count / head geometry)."""
        from ..inference.decode.block_pool import BlockKVCache

        cfg = self.cfg
        quant = self._resolve_cache_quant(quant)
        if dtype is None:
            dtype = self.transformer.wte.weight.dtype
        suffix = (cfg.num_kv_heads, cfg.head_dim)
        if quant == "int8":
            layer = ((suffix, jnp.int8), ((cfg.num_kv_heads,), jnp.float32),
                     (suffix, jnp.int8), ((cfg.num_kv_heads,), jnp.float32))
        else:
            layer = ((suffix, dtype), (suffix, dtype))
        return BlockKVCache(num_blocks, block_size,
                            [layer] * cfg.num_layers, quant=quant,
                            name=name)

    def decode_step(self, input_ids, caches, pos):
        """Cached decode step: logits for input_ids at global offset pos
        plus updated caches (the generation fast path)."""
        hidden, new_caches = self.transformer.forward_step(
            input_ids, caches, pos)
        return self._project(hidden), new_caches

    def loss(self, input_ids, labels=None, position_ids=None):
        """Causal LM loss. labels defaults to input_ids (shift happens here).

        The shift slices the *hidden* states before the vocab projection:
        slicing logits afterwards would force a copy of the full [B,S,V]
        logits (1.6 GB at the flagship shape) that the projection of the
        sliced hidden never materializes."""
        if labels is None:
            labels = input_ids
        hidden = self.transformer(input_ids, position_ids)[:, :-1, :]
        shift_logits = self._project(hidden)
        shift_labels = labels[:, 1:]
        return F.cross_entropy(
            ops.reshape(shift_logits, [-1, self.cfg.vocab_size]),
            ops.reshape(shift_labels, [-1]),
            reduction="mean")


def gpt(name="gpt_base", **overrides):
    d = dict(CONFIGS[name])
    d.update(overrides)
    return GPTForCausalLM(GPTConfig(**d))


def flops_per_token(cfg: GPTConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (fwd+bwd ≈ 6*N + attention term) for
    MFU accounting (BASELINE.md north-star)."""
    n_params = (
        cfg.vocab_size * cfg.hidden_size * (1 if cfg.tie_word_embeddings else 2)
        + cfg.num_layers * (
            cfg.hidden_size * (cfg.num_heads + 2 * cfg.num_kv_heads) * cfg.head_dim
            + cfg.num_heads * cfg.head_dim * cfg.hidden_size
            + cfg.hidden_size * cfg.intermediate_size * (3 if cfg.swiglu else 2)))
    attn = 12 * cfg.num_layers * cfg.hidden_size * seq_len
    return 6.0 * n_params + attn
