"""Model zoo (reference: python/paddle/vision/models + PaddleNLP zoo shapes
named in BASELINE.md)."""
from .gpt import (  # noqa: F401
    GPTConfig, GPTModel, GPTForCausalLM, gpt, CONFIGS as GPT_CONFIGS,
    flops_per_token, CacheQuantError,
)
from .resnet import (  # noqa: F401
    ResNet, BasicBlock, BottleneckBlock,
    resnet18, resnet34, resnet50, resnet101, resnet152,
)
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification, BertForMaskedLM,
    ErnieModel, ErnieForSequenceClassification, ErnieForMaskedLM,
    bert, bert_for_sequence_classification, bert_for_masked_lm,
)
from .generation import generate, GenerationConfig  # noqa: F401
from .conformer import (  # noqa: F401
    ConformerCTC, conformer_tiny, conformer_s,
)
