"""BERT/ERNIE-class encoder models (reference: the PaddleNLP BERT/ERNIE
families exercised by BASELINE config 2 — bidirectional transformer
encoder with token/position/segment embeddings, pooler, MLM and
sequence-classification heads).

TPU-native: the whole forward is jnp math over [B, S, H] activations
through the fused attention path (nn.functional.scaled_dot_product_
attention -> Pallas/XLA fused kernels); padding enters as an additive
mask so shapes stay static.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..core.tensor import Tensor


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    num_labels: int = 2


CONFIGS = {
    "bert_tiny": dict(vocab_size=1024, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=128,
                      max_position_embeddings=128),
    "bert_base": dict(),
    "bert_large": dict(hidden_size=1024, num_hidden_layers=24,
                       num_attention_heads=16, intermediate_size=4096),
    # ERNIE-3.0-base shares the BERT-base geometry (vocab differs)
    "ernie_base": dict(vocab_size=40000),
}


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = nn.Embedding(cfg.max_position_embeddings,
                                                cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32)[None])
        if token_type_ids is None:
            token_type_ids = Tensor(
                jnp.zeros(tuple(input_ids.shape), jnp.int32))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.qkv = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size)
        self.out = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.dropout_p = cfg.attention_probs_dropout_prob

    def forward(self, x, attn_bias=None):
        b, s, h = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(2)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_bias, dropout_p=self.dropout_p,
            is_causal=False, training=self.training)
        return self.out(out.reshape([b, s, h]))


class BertLayer(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(cfg)
        self.ln1 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.ln2 = nn.LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.act = F.gelu

    def forward(self, x, attn_bias=None):
        x = self.ln1(x + self.dropout(self.attention(x, attn_bias)))
        y = self.fc2(self.act(self.fc1(x)))
        return self.ln2(x + self.dropout(y))


class BertPooler(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, hidden):
        return self.dense(hidden[:, 0]).tanh()


class BertModel(nn.Layer):
    """Reference: BertModel — returns (sequence_output, pooled_output)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        self.layers = nn.LayerList(
            [BertLayer(cfg) for _ in range(cfg.num_hidden_layers)])
        self.pooler = BertPooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        bias = None
        if attention_mask is not None:
            m = attention_mask
            mv = m._value if isinstance(m, Tensor) else jnp.asarray(m)
            # [B, S] 1/0 keep-mask -> additive [B, 1, 1, S] bias
            bias = Tensor(
                jnp.where(mv[:, None, None, :].astype(bool), 0.0,
                          jnp.asarray(-1e9, jnp.float32)))
        for layer in self.layers:
            x = layer(x, bias)
        return x, self.pooler(x)


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.bert = BertModel(cfg)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)
        self.classifier = nn.Linear(cfg.hidden_size, cfg.num_labels)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids,
                              attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))

    def loss(self, input_ids, labels, token_type_ids=None,
             attention_mask=None):
        logits = self.forward(input_ids, token_type_ids, attention_mask)
        return F.cross_entropy(logits, labels).mean()


class BertForMaskedLM(nn.Layer):
    """MLM head tied to the word embedding (reference
    BertForMaskedLM/ErnieForPretraining)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True,
            default_initializer=nn.initializer.Constant(0.0))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids,
                           attention_mask=attention_mask)
        h = self.layer_norm(F.gelu(self.transform(seq)))
        w = self.bert.embeddings.word_embeddings.weight
        return h @ w.t() + self.bias

    def loss(self, input_ids, labels, ignore_index=-100, **kw):
        """labels: masked positions carry target ids, others ignore_index."""
        logits = self.forward(input_ids, **kw)
        v = self.cfg.vocab_size
        lbl = labels if isinstance(labels, Tensor) else Tensor(labels)
        return F.cross_entropy(logits.reshape([-1, v]), lbl.reshape([-1]),
                               ignore_index=ignore_index).mean()


ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification
ErnieForMaskedLM = BertForMaskedLM


def bert(name="bert_base", **overrides):
    d = dict(CONFIGS[name])
    d.update(overrides)
    return BertModel(BertConfig(**d))


def bert_for_sequence_classification(name="bert_base", **overrides):
    d = dict(CONFIGS[name])
    d.update(overrides)
    return BertForSequenceClassification(BertConfig(**d))


def bert_for_masked_lm(name="bert_base", **overrides):
    d = dict(CONFIGS[name])
    d.update(overrides)
    return BertForMaskedLM(BertConfig(**d))
