"""Conformer ASR encoder + CTC head.

Reference analog: the PaddleSpeech conformer stack the reference README
points at (paddlespeech/s2t/modules/conformer_convolution.py,
encoder.py) — conv subsampling, then blocks of
FFN/2 + MHSA + conv-module + FFN/2 (macaron), CTC loss on top.

TPU-native notes: the whole encoder is static-shape (padded batches +
length masks, no dynamic seq handling inside jit); attention rides the
shared flash-attention path when shapes allow; CTC loss comes from the
framework's functional set.
"""
from __future__ import annotations

from .. import nn, ops
from ..nn import functional as F


class ConvSubsampling(nn.Layer):
    """Two stride-2 convs: T -> T/4 (reference: subsampling.py Conv2dSubsampling4)."""

    def __init__(self, idim, odim):
        super().__init__()
        self.conv1 = nn.Conv2D(1, odim, 3, stride=2, padding=1)
        self.conv2 = nn.Conv2D(odim, odim, 3, stride=2, padding=1)
        # stride-2/padding-1 convs produce ceil(ceil(F/2)/2) frequency bins
        f_bins = ((idim + 1) // 2 + 1) // 2
        self.out = nn.Linear(odim * f_bins, odim)

    def forward(self, x):
        # x: [B, T, F] -> [B, 1, T, F]
        x = ops.unsqueeze(x, 1)
        x = F.relu(self.conv1(x))
        x = F.relu(self.conv2(x))          # [B, D, T/4, F/4]
        b, d, t, f = x.shape
        x = ops.transpose(x, [0, 2, 1, 3])  # [B, T/4, D, F/4]
        return self.out(ops.reshape(x, [b, t, d * f]))


class ConformerConvModule(nn.Layer):
    """Pointwise GLU -> depthwise conv -> BN-free LN -> pointwise."""

    def __init__(self, dim, kernel_size=15):
        super().__init__()
        self.norm = nn.LayerNorm(dim)
        self.pw1 = nn.Linear(dim, 2 * dim)
        self.dw = nn.Conv1D(dim, dim, kernel_size, groups=dim,
                            padding=kernel_size // 2)
        self.mid_norm = nn.LayerNorm(dim)
        self.pw2 = nn.Linear(dim, dim)

    def forward(self, x, pad_mask=None):
        h = self.pw1(self.norm(x))
        a, b = ops.split(h, 2, axis=-1)
        h = a * F.sigmoid(b)                      # GLU
        if pad_mask is not None:
            # LN/pw1 biases make padded rows nonzero again; the mask must
            # land immediately before the depthwise conv window slides
            h = h * pad_mask
        h = ops.transpose(h, [0, 2, 1])           # [B, D, T]
        h = self.dw(h)
        h = ops.transpose(h, [0, 2, 1])
        h = F.silu(self.mid_norm(h))
        return self.pw2(h)


class ConformerBlock(nn.Layer):
    def __init__(self, dim, num_heads=4, ff_mult=4, conv_kernel=15,
                 dropout=0.0):
        super().__init__()
        self.ff1_norm = nn.LayerNorm(dim)
        self.ff1a = nn.Linear(dim, dim * ff_mult)
        self.ff1b = nn.Linear(dim * ff_mult, dim)
        self.attn_norm = nn.LayerNorm(dim)
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = nn.Linear(dim, 3 * dim)
        self.attn_out = nn.Linear(dim, dim)
        self.conv = ConformerConvModule(dim, conv_kernel)
        self.ff2_norm = nn.LayerNorm(dim)
        self.ff2a = nn.Linear(dim, dim * ff_mult)
        self.ff2b = nn.Linear(dim * ff_mult, dim)
        self.final_norm = nn.LayerNorm(dim)
        self.drop = nn.Dropout(dropout)

    def _mhsa(self, x, attn_mask=None):
        b, t, d = x.shape
        q, k, v = ops.split(self.qkv(self.attn_norm(x)), 3, axis=-1)

        def heads(z):
            return ops.reshape(z, [b, t, self.num_heads, self.head_dim])

        out = F.scaled_dot_product_attention(
            heads(q), heads(k), heads(v), attn_mask=attn_mask,
            is_causal=False, training=self.training)
        return self.attn_out(ops.reshape(out, [b, t, d]))

    def forward(self, x, attn_mask=None, pad_mask=None):
        x = x + 0.5 * self.drop(self.ff1b(F.silu(self.ff1a(self.ff1_norm(x)))))
        x = x + self.drop(self._mhsa(x, attn_mask))
        # depthwise conv mixes across time: padding is masked INSIDE the
        # conv module (post-GLU), since its own LN/pointwise biases would
        # otherwise re-populate padded rows before the window slides
        x = x + self.drop(self.conv(x, pad_mask))
        x = x + 0.5 * self.drop(self.ff2b(F.silu(self.ff2a(self.ff2_norm(x)))))
        return self.final_norm(x)


class ConformerCTC(nn.Layer):
    """Conformer encoder with a CTC vocabulary head (reference: the s2t
    CTC training path)."""

    def __init__(self, feat_dim=80, dim=144, num_blocks=4, num_heads=4,
                 vocab_size=256, conv_kernel=15, dropout=0.0):
        super().__init__()
        self.subsample = ConvSubsampling(feat_dim, dim)
        self.blocks = nn.LayerList(
            [ConformerBlock(dim, num_heads, conv_kernel=conv_kernel,
                            dropout=dropout) for _ in range(num_blocks)])
        self.ctc_head = nn.Linear(dim, vocab_size + 1)  # +1 blank

    def forward(self, feats, feat_lengths=None):
        """feats: [B, T, F] log-mel features -> [B, T', vocab+1] logits
        with T' = ceil(T/4). `feat_lengths` [B]: true pre-subsampling
        lengths of zero-padded batches — padded frames are zeroed between
        blocks so conv/attention context never leaks across the pad
        boundary."""
        x = self.subsample(feats)
        mask = attn_mask = None
        if feat_lengths is not None:
            tl = self.subsampled_lengths(feat_lengths)
            t = x.shape[1]
            pos = ops.unsqueeze(ops.arange(t, dtype="int64"), 0)
            valid = pos < ops.unsqueeze(tl, -1)            # [B, T'] bool
            mask = ops.unsqueeze(ops.cast(valid, x.dtype), -1)
            # softmax must not place weight on padded KEYS:
            # [B, 1, 1, T'] additive mask broadcast over heads and queries
            attn_mask = ops.unsqueeze(ops.unsqueeze(
                (1.0 - ops.cast(valid, x.dtype)) * -1e9, 1), 1)
        for blk in self.blocks:
            if mask is not None:
                x = x * mask
            x = blk(x, attn_mask, mask)
        if mask is not None:
            x = x * mask
        return self.ctc_head(x)

    @staticmethod
    def subsampled_lengths(feat_lengths):
        """Pre- to post-subsampling length map (two stride-2 convs with
        padding 1): T' = ceil(ceil(T/2)/2)."""
        t1 = (feat_lengths + 1) // 2
        return (t1 + 1) // 2

    def loss(self, feats, labels, label_lengths=None, feat_lengths=None):
        """CTC loss. labels: [B, L] token ids in [1, vocab_size - 1],
        padded with 0 (id 0 is reserved for padding; the CTC blank is the
        LAST class, index vocab_size — do not use it as a token). Pass
        label_lengths explicitly if 0 is a real token; pass feat_lengths
        for zero-padded variable-length batches."""
        logits = self.forward(feats, feat_lengths)  # [B, T', V+1]
        b, t = logits.shape[0], logits.shape[1]
        log_probs = F.log_softmax(logits, axis=-1)
        log_probs = ops.transpose(log_probs, [1, 0, 2])  # [T', B, V+1]
        if feat_lengths is not None:
            input_lengths = ops.cast(self.subsampled_lengths(feat_lengths),
                                     "int64")
        else:
            input_lengths = ops.full([b], t, dtype="int64")
        if label_lengths is None:
            label_lengths = ops.sum(
                ops.cast(labels > 0, "int64"), axis=-1)
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=logits.shape[-1] - 1)


def conformer_tiny(**kw):
    return ConformerCTC(feat_dim=32, dim=48, num_blocks=2, num_heads=4,
                        vocab_size=30, **kw)


def conformer_s(**kw):
    """PaddleSpeech conformer-S-class config."""
    return ConformerCTC(feat_dim=80, dim=144, num_blocks=16, num_heads=4,
                        vocab_size=5000, **kw)
