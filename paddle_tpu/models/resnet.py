"""ResNet family (reference: python/paddle/vision/models/resnet.py —
BASELINE.md config 1: ResNet-50 ImageNet).

TPU notes: NCHW inputs for API parity with the reference (XLA on TPU
re-layouts convs internally); BatchNorm stats update only in train mode.
Opt-in (PADDLE_TPU_FUSED_RESBLOCK=1): on TPU + NHWC + bf16, stride-1
identity bottleneck blocks route through the fused Pallas kernel family
(ops/pallas/fused_resblock.py — the analog of the reference's
fused_scale_bias_relu_conv_bn CUDA kernel), which keeps the conv+BN+relu
chain VMEM-resident instead of streaming every link through HBM. Measured
slower than XLA's per-op path in-model, so DISABLED by default — see the
round-4 section of docs/resnet50_roofline.md for the full measurement
record. =force enables off-TPU (interpret mode, tests only).
"""
from __future__ import annotations

import os

from .. import nn
from .. import ops
from ..nn import functional as F


def _fused_blocks_mode():
    return os.environ.get("PADDLE_TPU_FUSED_RESBLOCK", "0")


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, data_format="NCHW"):
        super().__init__()
        df = self._data_format = data_format
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = nn.BatchNorm2D(width, data_format=df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False,
                               data_format=df)
        self.bn2 = nn.BatchNorm2D(width, data_format=df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = nn.BatchNorm2D(planes * self.expansion, data_format=df)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def _can_fuse(self, x=None):
        # every BN must itself be in batch-stats training mode: frozen-BN
        # fine-tuning (bn.eval() / use_global_stats) takes the unfused path
        for bn in (self.bn1, self.bn2, self.bn3):
            if not bn.training or bn._use_global_stats:
                return False
        if not (self.training and self.downsample is None
                and self.stride == 1 and self._data_format == "NHWC"
                and self.conv2._groups == 1
                and self.conv2._dilation == (1, 1)):
            return False
        mode = _fused_blocks_mode()
        if mode == "0":
            return False
        if mode == "force":
            return True
        # the kernels run bf16 MXU math; fusing an f32 model would silently
        # change its numerics, so require bf16 inputs outside force mode
        if x is not None and str(x.dtype) not in ("bfloat16",
                                                  "paddle.bfloat16"):
            return False
        import jax
        return jax.default_backend() == "tpu"

    def _forward_fused(self, x):
        from ..ops.pallas.fused_resblock import fused_block_impl
        from ..ops._helpers import apply
        y, mu1, v1, mu2, v2, mu3, v3 = apply(
            "fused_bottleneck", fused_block_impl,
            (x, self.conv1.weight, self.conv2.weight, self.conv3.weight,
             self.bn1.weight, self.bn1.bias, self.bn2.weight, self.bn2.bias,
             self.bn3.weight, self.bn3.bias),
            {"eps": float(self.bn1._epsilon)})
        from ..nn.functional.norm import update_running_stats
        n = x.size // x.shape[-1]
        for bn, mean, var in ((self.bn1, mu1, v1), (self.bn2, mu2, v2),
                              (self.bn3, mu3, v3)):
            update_running_stats(bn._mean, bn._variance, mean, var,
                                 bn._momentum, n)
        return y

    def forward(self, x):
        if self._can_fuse(x):
            return self._forward_fused(x)
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, data_format="NCHW"):
        super().__init__()
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False, data_format=df)
        self.bn1 = nn.BatchNorm2D(planes, data_format=df)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = nn.BatchNorm2D(planes, data_format=df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """data_format="NHWC" runs the whole network channels-last — on TPU the
    MXU-native conv layout (lane dim = channels), saving the relayout
    transposes XLA inserts around NCHW convs (BASELINE config 1 MFU work)."""

    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        df = self.data_format = data_format
        self.inplanes = 64
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False,
                               data_format=df)
        self.bn1 = nn.BatchNorm2D(64, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                                    data_format=df)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                nn.BatchNorm2D(planes * block.expansion, data_format=df),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, data_format=df))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes=num_classes, **kw)


def wide_resnet50_2(num_classes=1000, **kw):
    """Reference: vision/models/resnet.py wide_resnet50_2 (2x width)."""
    m = ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)
    return m


def wide_resnet101_2(num_classes=1000, **kw):
    m = ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)
    return m
