"""ResNet family (reference: python/paddle/vision/models/resnet.py —
BASELINE.md config 1: ResNet-50 ImageNet).

TPU notes: NCHW inputs for API parity with the reference (XLA on TPU
re-layouts convs internally); BatchNorm stats update only in train mode;
the whole network is conv+BN+relu chains that XLA fuses onto the MXU.
"""
from __future__ import annotations

from .. import nn
from .. import ops
from ..nn import functional as F


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, data_format="NCHW"):
        super().__init__()
        df = data_format
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = nn.BatchNorm2D(width, data_format=df)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False,
                               data_format=df)
        self.bn2 = nn.BatchNorm2D(width, data_format=df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = nn.BatchNorm2D(planes * self.expansion, data_format=df)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, data_format="NCHW"):
        super().__init__()
        df = data_format
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False, data_format=df)
        self.bn1 = nn.BatchNorm2D(planes, data_format=df)
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = nn.BatchNorm2D(planes, data_format=df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    """data_format="NHWC" runs the whole network channels-last — on TPU the
    MXU-native conv layout (lane dim = channels), saving the relayout
    transposes XLA inserts around NCHW convs (BASELINE config 1 MFU work)."""

    def __init__(self, block, depth_cfg, num_classes=1000, with_pool=True,
                 data_format="NCHW"):
        super().__init__()
        df = self.data_format = data_format
        self.inplanes = 64
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False,
                               data_format=df)
        self.bn1 = nn.BatchNorm2D(64, data_format=df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                                    data_format=df)
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=df)
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                nn.BatchNorm2D(planes * block.expansion, data_format=df),
            )
        layers = [block(self.inplanes, planes, stride, downsample,
                        data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, data_format=df))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)


def resnet152(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 8, 36, 3], num_classes=num_classes, **kw)


def wide_resnet50_2(num_classes=1000, **kw):
    """Reference: vision/models/resnet.py wide_resnet50_2 (2x width)."""
    m = ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)
    return m


def wide_resnet101_2(num_classes=1000, **kw):
    m = ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)
    return m
