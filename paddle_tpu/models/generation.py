"""Autoregressive generation (reference: the PaddleNLP generate() surface
backing BASELINE config 5's LLaMA inference).

TPU-native: decode runs as ONE jitted scan over a fixed max_new_tokens
window with a padded token buffer — static shapes, no per-token retraces.
Models exposing decode_step/init_cache (the GPT/LLaMA family) use the
KV-cached path by default: one prefill chunk, then O(context) attention
reads per new token; use_cache=False falls back to full-prefix re-runs
(fewer, larger ops — can win at toy sizes).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply
from ..distributed.functional import functionalize

__all__ = ["generate", "GenerationConfig"]


class GenerationConfig:
    def __init__(self, max_new_tokens=32, do_sample=False, temperature=1.0,
                 top_k=0, top_p=1.0, eos_token_id=None, pad_token_id=0,
                 seed=0, use_cache=True):
        self.max_new_tokens = int(max_new_tokens)
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = eos_token_id
        self.pad_token_id = int(pad_token_id)
        self.seed = int(seed)
        # KV-cached decode: O(context) work per new token instead of a full
        # prefix re-run — the only viable mode at LLM scale. (At toy sizes
        # per-op dispatch latency can dominate; use_cache=False re-runs the
        # prefix, which XLA executes as fewer, larger ops.)
        self.use_cache = bool(use_cache)


def _sample_logits(logits, key, cfg: GenerationConfig):
    # shared in-graph helpers with the serving engine's per-request
    # sampling (inference/sampling.py) — one set of top-k/top-p
    # semantics, online and offline (lazy import: models must not pull
    # the serving stack at import time)
    from ..inference import sampling as _samp

    if not cfg.do_sample:
        return jnp.argmax(logits, -1).astype(jnp.int32)
    logits = logits / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k > 0:
        logits = _samp.apply_top_k(logits, cfg.top_k)
    if cfg.top_p < 1.0:
        logits = _samp.apply_top_p(logits, cfg.top_p)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def _gen_jit_cache(model):
    """Compiled-decode cache on the model: jax.jit caches by function
    identity, so rebuilding the decode closure per generate() call would
    recompile every time (30s+ at LLM scale)."""
    cache = model.__dict__.get("_generate_jit_cache")
    if cache is None:
        cache = {}
        model.__dict__["_generate_jit_cache"] = cache
    return cache


def _cfg_key(cfg):
    return (cfg.max_new_tokens, cfg.do_sample, cfg.temperature, cfg.top_k,
            cfg.top_p, cfg.eos_token_id, cfg.pad_token_id, cfg.use_cache)


def _structure_key(model):
    """Fingerprint of the model's module structure so structural mutation
    between generate() calls (apply_lora, merge_lora, quantization convert,
    module swaps) invalidates the compiled program instead of silently
    replaying a stale one."""
    return tuple((n, type(s).__name__, getattr(s, "merged", None))
                 for n, s in model.named_sublayers())


def generate(model, input_ids, generation_config=None, **kwargs):
    """Greedy / top-k / top-p decoding. input_ids: [B, S] Tensor/ndarray.
    Returns [B, S + max_new_tokens] int32 (padded with pad_token_id after
    eos)."""
    cfg = generation_config or GenerationConfig(**kwargs)
    ids = input_ids._value if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    ids = ids.astype(jnp.int32)
    b, s = ids.shape
    total = s + cfg.max_new_tokens
    if cfg.max_new_tokens <= 0:
        return Tensor(ids)

    # inference mode: dropout inside a traced scan would bake ONE concrete
    # RNG key into the program (same mask every step) — decode in eval
    was_training = getattr(model, "training", False)
    model.eval()

    if cfg.use_cache and hasattr(model, "decode_step"):
        try:
            return _generate_cached(model, ids, cfg, b, s, total)
        finally:
            if was_training:
                model.train()

    jit_cache = _gen_jit_cache(model)
    sig = ("nocache", b, s, _cfg_key(cfg), _structure_key(model))
    cached = jit_cache.get(sig)
    if cached is not None:
        jitted, params, buffers = cached
        param_vals = {n: p._value for n, p in params.items()}
        buffer_vals = {n: v._value for n, v in buffers.items()}
        key = jax.random.PRNGKey(cfg.seed)
        try:
            out = jitted(param_vals, buffer_vals, ids, key)
        finally:
            if was_training:
                model.train()
        return Tensor(out)

    apply_fn, params, buffers = functionalize(
        model, method=lambda t: model.forward(t))
    param_vals = {n: p._value for n, p in params.items()}
    buffer_vals = {n: v._value for n, v in buffers.items()}

    def logits_fn(pv, bv, tokens):
        out, _ = apply_fn(pv, bv, Tensor(tokens))
        return out._value if isinstance(out, Tensor) else out

    eos = -1 if cfg.eos_token_id is None else int(cfg.eos_token_id)

    def decode(pv, bv, ids0, key):
        buf = jnp.full((b, total), cfg.pad_token_id, jnp.int32)
        buf = buf.at[:, :s].set(ids0)
        done0 = jnp.zeros((b,), bool)

        def step(carry, i):
            buf, done, key = carry
            logits = logits_fn(pv, bv, buf)
            # next-token logits live at position i-1 (the last real token)
            last = jax.lax.dynamic_index_in_dim(
                logits, i - 1, axis=1, keepdims=False)
            key, sub = jax.random.split(key)
            nxt = _sample_logits(last.astype(jnp.float32), sub, cfg)
            nxt = jnp.where(done, cfg.pad_token_id, nxt)
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, nxt, i, axis=1)
            done = done | (nxt == eos)
            return (buf, done, key), None

        (buf, _, _), _ = jax.lax.scan(
            step, (buf, done0, key), jnp.arange(s, total))
        return buf

    key = jax.random.PRNGKey(cfg.seed)
    jitted = jax.jit(decode)
    jit_cache[sig] = (jitted, params, buffers)
    try:
        out = jitted(param_vals, buffer_vals, ids, key)
    finally:
        if was_training:
            model.train()
    return Tensor(out)


def _generate_cached(model, ids, cfg: GenerationConfig, b, s, total):
    """KV-cached decode: one prefill pass over the prompt, then a jitted
    scan of single-token steps against per-layer caches — O(total) attention
    reads per new token instead of a full-prefix re-run. The compiled
    program is cached on the model per (b, s, cfg) signature; cache buffers
    are donated so each call reuses their HBM."""
    jit_cache = _gen_jit_cache(model)
    # cache layout (bf16 pairs vs int8 quads) is part of the compiled
    # signature — a model toggling cache_quant must not reuse the program
    sig = ("cached", b, s, _cfg_key(cfg), _structure_key(model),
           getattr(model, "cache_quant", None))
    key = jax.random.PRNGKey(cfg.seed)

    cached = jit_cache.get(sig)
    if cached is not None:
        jitted, params, buffers = cached
        param_vals = {n: p._value for n, p in params.items()}
        buffer_vals = {n: v._value for n, v in buffers.items()}
        caches = model.init_cache(b, total)
        cache_vals = [tuple(t._value for t in entry) for entry in caches]
        return Tensor(jitted(param_vals, buffer_vals, ids, cache_vals, key))

    caches = model.init_cache(b, total)
    # entries are (k, v) bf16 pairs or (kq, ks, vq, vs) int8 quads
    cache_vals = [tuple(t._value for t in entry) for entry in caches]

    def wrapped(tokens, cache_vals, pos):
        cts = [tuple(Tensor(a) for a in entry) for entry in cache_vals]
        logits, new_caches = model.decode_step(
            Tensor(tokens), cts, Tensor(pos))
        return (logits._value,
                [tuple(t._value for t in nc) for nc in new_caches])

    apply_fn, params, buffers = functionalize(model, method=wrapped)
    param_vals = {n: p._value for n, p in params.items()}
    buffer_vals = {n: v._value for n, v in buffers.items()}

    eos = -1 if cfg.eos_token_id is None else int(cfg.eos_token_id)

    def decode(pv, bv, ids0, cache_vals, key):
        # prefill the whole prompt in one chunk
        (logits, cache_vals), _ = apply_fn(
            pv, bv, ids0, cache_vals, jnp.asarray(0, jnp.int32))
        key, sub = jax.random.split(key)
        nxt = _sample_logits(logits[:, -1].astype(jnp.float32), sub, cfg)
        buf = jnp.full((b, total), cfg.pad_token_id, jnp.int32)
        buf = buf.at[:, :s].set(ids0)
        buf = buf.at[:, s].set(nxt)
        done0 = nxt == eos

        def step(carry, i):
            buf, cache_vals, done, key = carry
            tok = jax.lax.dynamic_slice_in_dim(buf, i - 1, 1, axis=1)
            (logits, cache_vals), _ = apply_fn(
                pv, bv, tok, cache_vals,
                (i - 1).astype(jnp.int32))
            key, sub = jax.random.split(key)
            nxt = _sample_logits(logits[:, -1].astype(jnp.float32), sub,
                                 cfg)
            nxt = jnp.where(done, cfg.pad_token_id, nxt)
            buf = jax.lax.dynamic_update_index_in_dim(buf, nxt, i, axis=1)
            done = done | (nxt == eos)
            return (buf, cache_vals, done, key), None

        if total > s + 1:
            (buf, _, _, _), _ = jax.lax.scan(
                step, (buf, cache_vals, done0, key),
                jnp.arange(s + 1, total))
        return buf

    jitted = jax.jit(decode, donate_argnums=(3,))
    jit_cache[sig] = (jitted, params, buffers)
    out = jitted(param_vals, buffer_vals, ids, cache_vals, key)
    return Tensor(out)
