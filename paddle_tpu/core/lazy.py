"""Lazy op segments: compiled subgraphs between graph breaks.

Reference analog: SOT's partial-graph compilation — the reference's
opcode translator executes *compiled subgraphs between graph breaks* and
resumes tracing after them
(python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py:1473,
break taxonomy jit/sot/utils/exceptions.py:38). Our to_static traces
whole functions; when a function contains an unconvertible construct the
round-3 contract dropped the WHOLE call to per-op eager execution.

TPU-native v2 (this module): in fallback mode, `dispatch.apply` defers
ops into a *segment* instead of executing them. The segment flushes — as
ONE composite op through the normal `apply` path (so it gets the per-op
jit cache, the tape GradNode, and a compiled VJP for free) — exactly when
a real value is demanded: `float(x)`, `.numpy()`, tensor-dependent python
control flow, or any library code touching `._value`. Everything between
two such break points therefore runs as one XLA-compiled subgraph, and
the breaking construct itself runs on real values, after which recording
resumes. This is the define-by-run equivalent of the reference's
"compile the pieces around the break" contract, with the break points
discovered dynamically instead of from bytecode.

Monitor counters (utils/monitor): `lazy_segment_ops` (ops that were
deferred), `lazy_segment_flushes` (compiled-subgraph executions),
`lazy_segment_fallback_ops` (ops a segment could not defer — executed
eagerly after a flush).
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp

from . import monitor

__all__ = ["lazy_segments", "lazy_recorder", "PendingValue", "EngineRef"]


class EngineRef:
    """Lazy binding of a Tensor to externally-managed device state.

    The distributed engine donates its parameter buffers every step, so a
    live Parameter's current value is whatever the engine's state dict
    holds *now*. Instead of rewriting every Parameter's `_value` after
    each step (a Python loop of property-setter work on the hot path),
    the engine installs one EngineRef per Parameter at construction:
    `_value` reads resolve through `fetch()` against the live engine
    state, and shape/dtype queries stay host-only. Writes through the
    `_value` setter simply replace the ref; the engine detects that
    (identity check) and adopts the external value on its next step.
    """

    __slots__ = ("fetch", "shape", "dtype")

    def __init__(self, fetch, shape, dtype):
        self.fetch = fetch
        self.shape = tuple(shape)
        self.dtype = dtype

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= int(d)
        return n


class PendingValue:
    """Placeholder stored in Tensor._v_ while the producing segment has
    not flushed. Carries the aval so shape/dtype queries stay lazy."""

    __slots__ = ("aval", "recorder", "slot")

    def __init__(self, aval, recorder, slot):
        self.aval = aval
        self.recorder = recorder
        self.slot = slot

    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        import numpy as np

        return int(np.prod(self.aval.shape)) if self.aval.shape else 1


# (impl, statics_items, input aval signature) -> output avals. eval_shape
# re-traces the impl through abstract interpretation every call (~100us+);
# recorded programs repeat identically every step, so memoize.
_EVAL_CACHE: dict = {}


def _segment_impl(*arrays, prog=()):
    """Replay a recorded program. arrays = the segment's external inputs;
    prog rows are (impl, statics_items, in_slots, n_outs) with slots
    ('x', i) = external input i, ('p', j) = pending value j. Returns the
    tuple of ALL pending values (any of them may be consumed later)."""
    pend = []
    for impl, st_items, in_slots, n_outs in prog:
        args = [arrays[i] if kind == "x" else pend[i]
                for kind, i in in_slots]
        out = impl(*args, **dict(st_items))
        if isinstance(out, (tuple, list)):
            pend.extend(out)
        else:
            pend.append(out)
    return tuple(pend)


class SegmentRecorder:
    def __init__(self):
        self.records = []       # (impl, statics_items, in_slots, n_outs)
        self.inputs = []        # external operands (Tensor or raw)
        self._input_ids = {}    # id(obj) -> input slot
        self.pending = []       # Tensor objects awaiting values
        self.flushing = False
        self.had_grad = False   # any recorded op needed gradients

    # -- recording ---------------------------------------------------------

    def maybe_record(self, name, impl, tensor_args, statics):
        """Try to defer this op. Returns the pending output Tensor(s), or
        NotImplemented if the op must run eagerly (after a flush)."""
        from .tensor import Tensor

        statics = statics or {}
        in_slots = []
        metas = []        # (shape, dtype) | raw scalar — for sig + avals
        for t in tensor_args:
            if isinstance(t, Tensor):
                v = t._v_
                if type(v) is PendingValue:
                    if v.recorder is not self:
                        return NotImplemented  # foreign segment: bail
                    in_slots.append(("p", v.slot))
                    metas.append((v.aval.shape, v.aval.dtype))
                    continue
                in_slots.append(("x", self._ext_slot(t)))
                metas.append((v.shape, v.dtype))
            else:
                in_slots.append(("x", self._ext_slot(t)))
                metas.append(t)
        try:
            st_items = tuple(sorted(statics.items())) if statics else ()
            ck = (impl, st_items, tuple(
                m if type(m) is tuple else (type(m), m) for m in metas))
            out_aval = _EVAL_CACHE.get(ck)
            if out_aval is None:
                aval_args = [
                    jax.ShapeDtypeStruct(*m) if type(m) is tuple else m
                    for m in metas]
                out_aval = jax.eval_shape(
                    lambda *a: impl(*a, **statics), *aval_args)
                _EVAL_CACHE[ck] = out_aval
        except Exception:  # tpu-lint: disable=TL007 — deliberate probe:
            # ANY trace failure (shape-/value-dependent impl, unhashable
            # statics, non-hashable scalar arg) just means this op is a
            # break point — the caller flushes and runs it eagerly
            return NotImplemented

        out_is_seq = isinstance(out_aval, (tuple, list))
        out_avals = list(out_aval) if out_is_seq else [out_aval]
        base = len(self.pending)
        self.records.append((impl, st_items, tuple(in_slots),
                             len(out_avals)))
        from .dispatch import is_grad_enabled

        any_grad = is_grad_enabled() and any(
            isinstance(t, Tensor) and not t.stop_gradient
            for t in tensor_args)
        if any_grad:
            self.had_grad = True
        outs = []
        for i, av in enumerate(out_avals):
            t = Tensor.__new__(Tensor)
            t._v_ = PendingValue(av, self, base + i)
            t.stop_gradient = not any_grad
            t.grad = None
            t._grad_node = None
            t._out_idx = 0
            t.name = None
            t.persistable = False
            t._hooks = None
            t.trainable = True
            self.pending.append(t)
            outs.append(t)
        monitor.increment("lazy_segment_ops")
        return tuple(outs) if out_is_seq else outs[0]

    def _ext_slot(self, obj):
        slot = self._input_ids.get(id(obj))
        if slot is None:
            slot = len(self.inputs)
            self._input_ids[id(obj)] = slot
            self.inputs.append(obj)
        return slot

    # -- flushing ----------------------------------------------------------

    def flush(self):
        """Execute all recorded ops as one compiled composite op and fill
        the pending tensors (tape-wired through the normal apply path)."""
        if not self.records or self.flushing:
            return
        from .dispatch import apply

        records = self.records
        inputs = self.inputs
        pending = self.pending
        self.records, self.inputs, self.pending = [], [], []
        self._input_ids = {}
        prog = tuple(records)
        had_grad = self.had_grad
        self.had_grad = False
        self.flushing = True
        from .dispatch import set_grad_enabled, is_grad_enabled

        prev_grad = is_grad_enabled()
        try:
            if had_grad and not prev_grad:
                # a value read under no_grad() (logging, metrics) must not
                # silently drop the gradients of ops recorded WITH grad
                set_grad_enabled(True)
            outs = apply("lazy_segment", _segment_impl, inputs,
                         {"prog": prog})
        finally:
            set_grad_enabled(prev_grad)
            self.flushing = False
        outs = outs if isinstance(outs, tuple) else (outs,)
        for t, o in zip(pending, outs):
            v = t._v_
            if not (type(v) is PendingValue and v.recorder is self):
                continue  # rebound by the user since recording: keep theirs
            t._v_ = o._v_
            t._grad_node = o._grad_node
            t._out_idx = o._out_idx
            t.stop_gradient = o.stop_gradient
        monitor.increment("lazy_segment_flushes")


class _State(threading.local):
    def __init__(self):
        self.stack = []


_state = _State()


def lazy_recorder():
    """The active recorder for this thread, or None."""
    return _state.stack[-1] if _state.stack else None


class lazy_segments:
    """Context manager enabling segment recording on this thread."""

    def __enter__(self):
        self._rec = SegmentRecorder()
        _state.stack.append(self._rec)
        return self._rec

    def __exit__(self, exc_type, exc, tb):
        rec = _state.stack.pop()
        if exc_type is None:
            rec.flush()
        return False
