"""Runtime monitor counters (reference: paddle/fluid/platform/monitor.h —
the `Monitor` singleton of named int64 stats, `STAT_INT` registration and
python `get_int_stats`-style readout used for fleet/PS observability).

TPU-native shape: a process-local thread-safe registry of named integer
counters; framework subsystems increment a handful of built-ins (op
dispatches, jit compiles, dataloader batches, async PS pushes) and user
code can register its own. Cheap by construction — one dict add under the
GIL per event."""
from __future__ import annotations

from ..analysis import locks as _locks

__all__ = ["increment", "get", "get_all", "reset", "counter_names"]

_lock = _locks.new_lock("monitor.counters")
_counters: dict = {}


def increment(name, delta=1):
    """Add `delta` to counter `name` (auto-registers on first use,
    like STAT_INT's lazy registry)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(delta)


def get(name):
    """Current value (0 for never-incremented counters, matching the
    reference's default-constructed stats)."""
    with _lock:
        return _counters.get(name, 0)


def get_all():
    """Snapshot of every counter (reference: monitor's stat map dump)."""
    with _lock:
        return dict(_counters)


def reset(name=None):
    with _lock:
        if name is None:
            _counters.clear()
        else:
            _counters.pop(name, None)


def counter_names():
    with _lock:
        return sorted(_counters)
