"""Type-promotion parity (reference: paddle/phi/common/type_promotion.h
`promoteTypes` 12x12 lookup, `NeedTypePromotion`, and the eager hook
paddle/fluid/eager/type_promotion_utils.h).

JAX's promotion lattice (`jnp.promote_types`) is *identical* to the
reference's `_promoteTypesLookup` table over all 12 paddle dtypes —
verified exhaustively by tests/test_type_promotion.py — including the
corners the reference special-cases:

- ``uint8 x int8 -> int16`` (unsigned/signed same width widens),
- ``bfloat16 x float16 -> float32`` (the two half floats join at f32),
- ``bool`` is the promotion identity,
- any float dominates any int.

The one *runtime* divergence is width policy, not the table: with
``jax_enable_x64`` off (TPU default), 64-bit results are materialized at
32-bit width (``int32 x int64 -> int32`` at run time, ``float64``
arithmetic runs in ``float32``). This is an explicit de-scope: the table
below answers dtype queries with full-width reference semantics, while
runtime kernels follow the platform width policy. Enable
``JAX_ENABLE_X64`` for bit-parity on 64-bit corners.

The reference applies tensor-tensor promotion only when both operands are
(distinct) floating types (`NeedTypePromotion`, type_promotion.h:107);
integer pairs must match dtypes. Our dispatch is more permissive (jnp
promotes integer pairs by the same table instead of raising) — a
documented superset of the reference contract.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import dtype as dtypes

__all__ = ["promote_types", "need_type_promotion", "get_promote_dtype"]

_FLOATS = ("float16", "float32", "float64", "bfloat16")


def _canon(d):
    c = dtypes.convert_dtype(d)
    return str(c) if c is not None else str(jnp.dtype(d))


def promote_types(x_dtype, y_dtype):
    """Reference: phi::promoteTypes (type_promotion.h:50). Returns the
    full-width promoted dtype name for any pair of the 12 paddle dtypes."""
    return str(jnp.promote_types(_canon(x_dtype), _canon(y_dtype)))


def need_type_promotion(x_dtype, y_dtype):
    """Reference: phi::NeedTypePromotion (type_promotion.h:107) — tensor x
    tensor promotion fires only for two distinct floating dtypes."""
    x, y = _canon(x_dtype), _canon(y_dtype)
    return x != y and x in _FLOATS and y in _FLOATS


def get_promote_dtype(op_name, x_dtype, y_dtype):
    """Reference: phi::GetPromoteDtype (type_promotion.h:96). Intentional
    superset: the reference special-cases only 'greater_than'; we return
    bool for all six comparison ops (behaviorally benign — comparison
    outputs are bool either way)."""
    if op_name in ("greater_than", "less_than", "greater_equal",
                   "less_equal", "equal", "not_equal"):
        return "bool"
    return promote_types(x_dtype, y_dtype)
