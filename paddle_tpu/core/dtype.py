"""Dtype system for paddle_tpu.

Mirrors the reference framework's dtype surface (paddle/phi/common/data_type.h and
python/paddle dtype aliases) on top of numpy/jax dtypes. TPU-first: bfloat16 is a
first-class dtype; float64 is supported but discouraged (TPU emulates it slowly).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtypes (jnp dtype objects). These are the public `paddle_tpu.float32`
# etc. aliases, matching the reference's `paddle.float32` surface.
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    # convenience aliases
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}

_default_dtype = jnp.float32


def set_default_dtype(d):
    """Set default floating dtype (reference: paddle.set_default_dtype,
    python/paddle/framework/framework.py)."""
    global _default_dtype
    _default_dtype = convert_dtype(d)


def get_default_dtype():
    return _default_dtype


def convert_dtype(dtype):
    """Normalize str/np.dtype/jnp dtype to a canonical numpy dtype object."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR_TO_DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return np.dtype(_STR_TO_DTYPE[dtype])
    return np.dtype(dtype)


def is_floating(dtype) -> bool:
    d = np.dtype(dtype)
    return jnp.issubdtype(d, jnp.floating)


def is_integer(dtype) -> bool:
    d = np.dtype(dtype)
    return jnp.issubdtype(d, jnp.integer)


def is_complex(dtype) -> bool:
    d = np.dtype(dtype)
    return jnp.issubdtype(d, jnp.complexfloating)
