from .tensor import Tensor, to_tensor
from .dtype import (
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, set_default_dtype, get_default_dtype,
    convert_dtype,
)
from .dispatch import no_grad, is_grad_enabled, set_grad_enabled
