"""Forward-mode AD routing flag.

The hard-label cross-entropy and affine layer_norm run through
`jax.custom_vjp` fast paths (hand-written backwards, see
nn/functional/loss.py and norm.py). custom_vjp functions reject
forward-mode differentiation by design, so the public
`paddle.incubate.autograd.jvp`/`forward_grad`/`hessian` entry points wrap
their traces in `forward_ad()`; ops consult `forward_ad_active()` at
dispatch time and fall back to the plain-jnp compositions (which
differentiate in any mode). The flag is threaded into the op's static
cache key, so forward- and reverse-mode traces get separate compiled
entries and never alias."""
from __future__ import annotations

import contextlib
import threading

_state = threading.local()


def forward_ad_active():
    return getattr(_state, "depth", 0) > 0


@contextlib.contextmanager
def forward_ad():
    _state.depth = getattr(_state, "depth", 0) + 1
    try:
        yield
    finally:
        _state.depth -= 1
