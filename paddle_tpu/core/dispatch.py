"""Eager op dispatch with tape-based autograd over jitted JAX primitives.

Design (TPU-native replacement for the reference's eager stack):

The reference dispatches each eager op through a generated `*_ad_func` that
records a GradNode on the tape and calls a phi kernel
(paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:251,
paddle/fluid/eager/grad_node_info.h:197). Here every op is a *pure JAX
function*; eager execution runs it under a cached `jax.jit` (one compilation
per (op, static-args, shapes) — XLA is the kernel library). Autograd records a
lightweight tape node holding the op's input arrays; the backward pass calls a
cached jitted VJP (`jax.vjp` inside jit) so gradients are also compiled. The
residual policy is "store inputs, recompute forward inside the VJP" — per-op
rematerialization, which on TPU trades cheap FLOPs for HBM.

The fully-jitted training path (paddle_tpu.jit) bypasses this tape entirely by
tracing the whole step; this module is the define-by-run compatibility layer.
"""
from __future__ import annotations

import contextlib
import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from . import monitor

from .lazy import _state as _lazy_state

__all__ = [
    "apply",
    "no_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "GradNode",
]


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True


_state = _State()


def is_grad_enabled() -> bool:
    return _state.grad_enabled


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


_saved_tensors_hooks: list = []


@contextlib.contextmanager
def saved_tensors_hooks(pack_hook, unpack_hook):
    """Intercept tensors the tape saves for backward (reference:
    paddle.autograd.saved_tensors_hooks — e.g. offload-to-host packs).
    pack_hook(array) runs when an op records its inputs; unpack_hook runs
    once when the node's VJP first needs them."""
    _saved_tensors_hooks.append((pack_hook, unpack_hook))
    try:
        yield
    finally:
        _saved_tensors_hooks.pop()


@contextlib.contextmanager
def no_grad():
    """Context manager disabling tape recording (reference: paddle.no_grad)."""
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


# --------------------------------------------------------------------------
# Cached jitted forward / vjp per (impl, static-args) pair.
# --------------------------------------------------------------------------

_jit_cache: dict = {}


def _hashable(v):
    if isinstance(v, (list,)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return (v.shape, str(v.dtype), v.tobytes())
    return v


def _get_fwd(impl, statics_key, statics):
    key = ("fwd", impl, statics_key)
    fn = _jit_cache.get(key)
    if fn is None:
        fn = jax.jit(partial(impl, **statics))
        _jit_cache[key] = fn
        monitor.increment("op_jit_program_total")
    return fn


def _get_fwd_vjp(impl, statics_key, n_primals, statics):
    """Jitted function: primals -> (out, residual-free). We don't keep the
    closure; backward re-runs the forward inside the jitted VJP below."""
    return _get_fwd(impl, statics_key, statics)


def _vjp_callable(impl, statics, n_primals):
    def run(primals, cotangent):
        f = partial(impl, **statics)
        out, vjp_fn = jax.vjp(f, *primals)
        # Cotangents may arrive in a different float dtype than the output
        # (mixed-precision tapes: an fp32 loss feeding a bf16 matmul). Cast to
        # the output aval's dtype — XLA fuses the convert into the vjp.
        cotangent = jax.tree_util.tree_map(
            lambda c, o: jnp.asarray(c, o.dtype) if c.dtype != o.dtype else c,
            cotangent, out)
        return vjp_fn(cotangent)

    return run


def _get_vjp(impl, statics_key, n_primals, statics):
    key = ("vjp", impl, statics_key, n_primals)
    fn = _jit_cache.get(key)
    if fn is None:
        fn = jax.jit(_vjp_callable(impl, statics, n_primals))
        _jit_cache[key] = fn
    return fn


# --------------------------------------------------------------------------
# create_graph=True path: the VJP itself dispatched as a taped op.
#
# Reference analog: egr::RunBackward with create_graph — grad-node execution
# runs through the normal eager dispatch so new GradNodes are recorded for the
# cotangent computation (paddle/fluid/eager/backward.cc:428). Here the VJP of
# op `impl` becomes an op in its own right: a pure function of
# (primals..., cotangents...) returning one grad per primal. Dispatching it via
# `apply` makes the produced gradients differentiable (grad-of-grad), and
# higher orders nest for free — the taped VJP of a taped VJP is just another
# cached impl.
# --------------------------------------------------------------------------

_taped_vjp_cache: dict = {}


def taped_vjp_impl(impl, n_primals, out_is_seq):
    key = (impl, n_primals, out_is_seq)
    fn = _taped_vjp_cache.get(key)
    if fn is None:
        def run(*args, **statics):
            primals, cts = args[:n_primals], args[n_primals:]
            f = partial(impl, **statics)
            out, vjp_fn = jax.vjp(f, *primals)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            cts = tuple(
                jnp.asarray(c, o.dtype)
                if hasattr(c, "dtype") and c.dtype != o.dtype else c
                for c, o in zip(cts, outs))
            grads = vjp_fn(tuple(cts) if out_is_seq else cts[0])
            # float0 cotangents (integer primals) can't cross a jit boundary
            # as Tensor payloads; substitute dead float zeros (their metas
            # carry needs_grad=False so the engine never uses them).
            return tuple(
                jnp.zeros(p.shape, jnp.float32)
                if hasattr(g, "dtype") and g.dtype == jax.dtypes.float0 else g
                for g, p in zip(grads, primals))

        run.__name__ = f"{getattr(impl, '__name__', 'op')}_taped_vjp"
        _taped_vjp_cache[key] = fn = run
    return fn


# --------------------------------------------------------------------------
# Tape
# --------------------------------------------------------------------------


class GradNode:
    """A recorded op on the eager tape.

    Reference analog: egr::GradNodeBase (grad_node_info.h:197). Holds the pure
    impl + static args + input arrays; `run_vjp` computes input cotangents via
    a cached jitted VJP.
    """

    __slots__ = (
        "name",
        "impl",
        "statics",
        "statics_key",
        "input_arrays",
        "input_metas",
        "input_versions",
        "n_outputs",
        "out_is_seq",
        "_id",
        "_unpack_hook",
    )

    _counter = [0]

    def __init__(self, name, impl, statics, statics_key, input_arrays, input_metas, n_outputs, out_is_seq):
        self.name = name
        self.impl = impl
        self.statics = statics
        self.statics_key = statics_key
        self.input_arrays = input_arrays
        self._unpack_hook = None
        self.input_metas = input_metas  # list of (producer GradNode|None, out_idx, leaf Tensor|None, needs_grad)
        # Tensor versions at record time — the taped (create_graph) path
        # recomputes from live tensors and must refuse in-place-mutated ones
        # (reference analog: the eager tensor inplace_version check,
        # paddle/fluid/eager/tensor_wrapper.h).
        self.input_versions = [
            getattr(m[2], "_version", 0) if m[2] is not None else 0
            for m in input_metas]
        self.n_outputs = n_outputs
        self.out_is_seq = out_is_seq
        GradNode._counter[0] += 1
        self._id = GradNode._counter[0]

    def run_vjp(self, cotangents):
        """cotangents: list aligned with outputs (None entries filled with zeros)."""
        unpack = getattr(self, "_unpack_hook", None)
        if unpack is not None and self.input_arrays is not None:
            self.input_arrays = [unpack(a) for a in self.input_arrays]
            self._unpack_hook = None
        if self.input_arrays is None:
            raise RuntimeError(
                f"Trying to backward through op '{self.name}' a second time; "
                "the saved tensors were already released. Call backward with "
                "retain_graph=True to backward multiple times.")
        if self.out_is_seq:
            ct = tuple(cotangents)
        else:
            ct = cotangents[0]
        vjp = _get_vjp(self.impl, self.statics_key, len(self.input_arrays), self.statics)
        return vjp(tuple(self.input_arrays), ct)

    def run_vjp_taped(self, cotangents):
        """create_graph=True: dispatch the VJP through `apply` so the
        cotangent computation is itself recorded on the tape. `cotangents`
        entries are Tensors (tracked) or raw arrays (constants); returns a
        list of Tensors, one per input slot.

        Uses the live input Tensors from the metas — that is what links the
        new grad nodes back to the original graph for second order — guarded
        by a version check so an in-place mutation between forward and
        backward raises instead of silently changing the gradient. (Under
        AMP the live values are the pre-cast fp32 ones, so taped gradients
        are computed at full precision — an intentional, finer deviation
        from the snapshot path.) Saved-tensor unpack hooks only fire for
        slots with no live Tensor, and nothing is unpacked in place, so
        offloaded residuals stay offloaded."""
        if self.input_arrays is None:
            raise RuntimeError(
                f"Trying to backward through op '{self.name}' a second time; "
                "the saved tensors were already released. Call backward with "
                "retain_graph=True to backward multiple times.")
        unpack = getattr(self, "_unpack_hook", None)
        ins = []
        for meta, a, ver in zip(self.input_metas, self.input_arrays,
                                self.input_versions):
            t = meta[2]
            if t is not None:
                if getattr(t, "_version", 0) != ver:
                    raise RuntimeError(
                        f"Input of op '{self.name}' was modified by an "
                        "in-place operation after being used in the forward; "
                        "double-grad (create_graph=True) cannot recompute "
                        "through it. Clone the tensor before mutating it.")
                ins.append(t)
            else:
                ins.append(unpack(a) if unpack is not None else a)
        impl = taped_vjp_impl(self.impl, len(ins), self.out_is_seq)
        outs = apply(self.name + "_grad", impl, [*ins, *cotangents],
                     statics=self.statics)
        return list(outs) if isinstance(outs, (tuple, list)) else [outs]

    def release(self):
        self.input_arrays = None


# AMP hook: set by paddle_tpu.amp at import; returns target dtype for an op
# under the active autocast policy, or None (reference analog: the AMP cast
# logic generated into every ad_func, eager_amp_auto_cast.h:64).
_amp_cast_hook = None


def set_amp_cast_hook(fn):
    global _amp_cast_hook
    _amp_cast_hook = fn


# Profiler hook: set by paddle_tpu.profiler while recording; maps op name ->
# a span object with begin()/end() (reference analog: the RecordEvent
# emitted inside every generated ad_func).
_profile_hook = None


def set_profile_hook(fn):
    global _profile_hook
    _profile_hook = fn


# Static-capture hook: set by paddle_tpu.static while static mode is on;
# appends every dispatched op to the default Program (the reference appends
# OpDescs to the Program block instead, python/paddle/base/framework.py).
_static_capture_hook = None


def set_static_capture_hook(fn):
    global _static_capture_hook
    _static_capture_hook = fn


def apply(name, impl, tensor_args, statics=None, out_wrapper=None):
    hook = _profile_hook  # single read: may be unset concurrently by stop()
    if hook is None:
        return _apply(name, impl, tensor_args, statics, out_wrapper)
    ev = hook(name)
    ev.begin()
    try:
        return _apply(name, impl, tensor_args, statics, out_wrapper)
    finally:
        ev.end()


def _apply(name, impl, tensor_args, statics=None, out_wrapper=None):
    """Dispatch one eager op.

    Args:
      name: op name (for debugging / profiling).
      impl: pure function (array_args..., **statics) -> array | tuple of arrays.
      tensor_args: sequence of Tensor (or raw array) positional operands.
      statics: dict of non-traced keyword args (must be hashable-ish).
      out_wrapper: optional callable mapping each output array -> Tensor
        (defaults to Tensor construction).

    Returns a Tensor or tuple of Tensors mirroring impl's output structure.
    """
    from .tensor import Tensor  # circular-safe

    rec = _lazy_state.stack[-1] if _lazy_state.stack else None
    if rec is not None and out_wrapper is not None:
        rec = None
    if rec is not None and _amp_cast_hook is not None:
        from ..amp import amp_state
        if amp_state().enabled:
            rec = None       # per-op autocast needs per-op names: no defer
    if rec is not None and not rec.flushing:
        from .. import flags as _flags
        if _flags.flag("check_nan_inf"):
            rec = None                     # per-op NaN isolation
    if rec is not None and not rec.flushing:
        res = rec.maybe_record(name, impl, tensor_args, statics)
        if res is not NotImplemented:
            return res
        # op declined deferral (shape/value-dependent impl): it is a break
        # point — materialize the segment, then run this op eagerly
        rec.flush()
        monitor.increment("lazy_segment_fallback_ops")

    monitor.increment("op_dispatch_total")
    statics = statics or {}
    statics_key = _hashable(statics)

    cast_to = _amp_cast_hook(name) if _amp_cast_hook is not None else None

    arrays = []
    metas = []
    any_grad = False
    for t in tensor_args:
        if isinstance(t, Tensor):
            v = t._value
            # host-offloaded operands (pinned_host params from
            # group_sharded_parallel(offload=True) etc.) stream to device
            # memory on use — XLA cannot mix memory spaces in one op
            mk = getattr(getattr(v, "sharding", None), "memory_kind", None)
            if mk in ("pinned_host", "unpinned_host"):
                from ..compat import has_device_memory_kind

                if has_device_memory_kind():
                    v = jax.device_put(
                        v, v.sharding.with_memory_kind("device"))
            if cast_to is not None and v.dtype != cast_to and jnp.issubdtype(v.dtype, jnp.floating):
                v = v.astype(cast_to)
            arrays.append(v)
            needs = (not t.stop_gradient) and _state.grad_enabled
            metas.append((t._grad_node, t._out_idx, t, needs))
            any_grad = any_grad or needs
        else:
            arrays.append(t)
            metas.append((None, 0, None, False))

    fwd = _get_fwd(impl, statics_key, statics)
    out = fwd(*arrays)

    out_is_seq = isinstance(out, (tuple, list))
    outs = list(out) if out_is_seq else [out]

    # numerical sanitizer (reference: FLAGS_check_nan_inf ->
    # eager/nan_inf_utils.cc per-op scan); debugging mode — forces a sync
    from .. import flags as _flags

    if _flags.flag("check_nan_inf"):
        for i, o in enumerate(outs):
            if isinstance(o, jax.core.Tracer):
                continue  # traced value: nothing concrete to scan
            if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.inexact) \
                    and not bool(jnp.isfinite(o).all()):
                msg = (f"NaN/Inf detected in output {i} of op '{name}' "
                       f"(shape {getattr(o, 'shape', ())})")
                if _flags.flag("check_nan_inf_level") >= 1:
                    import warnings

                    warnings.warn(msg)
                else:
                    raise RuntimeError(msg)

    node = None
    if any_grad:
        saved = arrays
        if _saved_tensors_hooks:
            pack, _ = _saved_tensors_hooks[-1]
            saved = [pack(a) for a in arrays]
        node = GradNode(name, impl, statics, statics_key, saved, metas, len(outs), out_is_seq)
        if _saved_tensors_hooks:
            node._unpack_hook = _saved_tensors_hooks[-1][1]

    wrapped = []
    for i, o in enumerate(outs):
        t = Tensor(o, stop_gradient=not any_grad)
        if node is not None:
            t._grad_node = node
            t._out_idx = i
        wrapped.append(t)

    if _static_capture_hook is not None:
        _static_capture_hook(name, impl, statics, tensor_args, wrapped)

    if out_is_seq:
        return tuple(wrapped)
    return wrapped[0]
