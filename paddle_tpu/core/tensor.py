"""Eager Tensor facade over jax.Array.

Reference analog: `paddle::Tensor` (paddle/phi/api/include/tensor.h:82) +
`AutogradMeta` (paddle/fluid/eager/autograd_meta.h:61). One Python object
bundles the immutable device buffer (a jax.Array, resident in TPU HBM via
PJRT), autograd metadata (producer GradNode, accumulated .grad, hooks), and
the mutable-tensor illusion: "in-place" APIs rebind `_value` to a fresh
functional result, which is the TPU-idiomatic way to express mutation (XLA
buffers are immutable; donation recovers the memory in jitted paths).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from . import lazy as _lazy
from .dispatch import is_grad_enabled


class Tensor:
    __slots__ = (
        "_v_",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_idx",
        "name",
        "persistable",
        "_hooks",
        "trainable",
        # DTensor annotations (distributed.auto_parallel): pending-Partial
        # mesh axes and the owning ProcessMesh
        "_partial_axes",
        "process_mesh",
        # in-place mutation counter (reference: TensorWrapper inplace_version
        # check) — read by the taped double-grad path; lazy-segment flushes
        # write _v_ directly and do NOT bump (same logical value)
        "_version",
        "__weakref__",
    )

    # _value is a property over the _v_ slot so lazy segments
    # (core/lazy.py) can defer execution: while a producing segment is
    # unflushed, _v_ holds a PendingValue; the first real-value demand
    # flushes the compiled subgraph. Shape/dtype queries stay lazy.
    @property
    def _value(self):
        v = self._v_
        tv = type(v)
        if tv is _lazy.PendingValue:
            v.recorder.flush()
            v = self._v_
        elif tv is _lazy.EngineRef:
            # engine-managed parameter: resolve against the live engine
            # state on every read (never cached — the engine donates and
            # replaces these buffers each step)
            v = v.fetch()
        return v

    @_value.setter
    def _value(self, v):
        cur = getattr(self, "_v_", None)
        if type(cur) is _lazy.PendingValue:
            # flush the recorder that OWNS this pending value (it may not
            # be the innermost one when segments nest)
            cur.recorder.flush()
        elif _lazy._state.stack:
            rec = _lazy._state.stack[-1]
            # rebinding a tensor the active segment references must flush
            # first, else the segment would replay stale values
            if id(self) in rec._input_ids:
                rec.flush()
        self._v_ = v
        self._version = getattr(self, "_version", 0) + 1

    def __init__(self, value, stop_gradient=True, name=None):
        if isinstance(value, Tensor):
            value = value._value
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        self._v_ = value
        self._version = 0
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_idx = 0
        self.name = name
        self.persistable = False
        self._hooks = None
        self.trainable = True

    # -- meta ------------------------------------------------------------
    @property
    def shape(self):
        return list(self._v_.shape)

    @property
    def dtype(self):
        return self._v_.dtype

    @property
    def ndim(self):
        return self._v_.ndim

    @property
    def size(self):
        return int(self._v_.size)

    @property
    def place(self):
        dev = list(self._value.devices())[0]
        return str(dev)

    @property
    def T(self):
        from .. import ops
        return ops.t(self)

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numel(self):
        return int(self._value.size)

    def dim(self):
        return self._value.ndim

    # -- conversion ------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self._value[args].item() if len(args) > 1 else np.asarray(self._value).flat[args[0]].item()
        return np.asarray(self._value).item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __bool__(self):
        try:
            return bool(np.asarray(self._value))
        except Exception as e:
            if "racer" in type(e).__name__ or "racer" in str(e):
                from ..jit.dy2static import Dy2StaticError, _GUIDE
                raise Dy2StaticError(
                    "bool() on a traced tensor: " + _GUIDE) from e
            raise

    def __len__(self):
        if self._value.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __hash__(self):
        return id(self)

    def __reduce__(self):
        # pickle via numpy so Tensors cross process boundaries (DataLoader
        # forkserver workers, dist.spawn); the tape does not survive
        return (_tensor_from_numpy,
                (np.asarray(self._value), self.stop_gradient, self.name))

    def __deepcopy__(self, memo):
        t = Tensor(self._value, stop_gradient=self.stop_gradient, name=self.name)
        t.persistable = self.persistable
        t.trainable = self.trainable
        memo[id(self)] = t
        return t

    # -- autograd --------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False,
                 create_graph=False):
        from ..autograd.backward_engine import run_backward

        run_backward([self], [grad_tensor], retain_graph=retain_graph,
                     create_graph=create_graph)

    def register_hook(self, hook):
        """Register a gradient hook (reference: tensor hooks in
        eager/grad_node_info.h). Returns a removable handle."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        tensor = self
        idx = len(self._hooks) - 1

        class _Handle:
            def remove(self):
                tensor._hooks[idx] = None

        return _Handle()

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._value))
        else:
            self.grad = None

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._grad_node = None
        self._out_idx = 0
        self.stop_gradient = True
        return self

    def clone(self):
        from .. import ops
        return ops.assign(self)

    # -- mutation (functional under the hood) ----------------------------
    def _set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        self._value = jnp.asarray(value)
        return self

    def set_value(self, value):
        return self._set_value(value)

    def copy_(self, other, blocking=True):
        return self._set_value(other)

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    # -- dtype/device ----------------------------------------------------
    def astype(self, dtype):
        from .. import ops
        return ops.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # device moves are XLA-managed; only dtype conversion is meaningful
        for a in list(args) + list(kwargs.values()):
            try:
                d = dtypes.convert_dtype(a)
            except (ValueError, TypeError):
                continue
            if d is not None:
                return self.astype(d)
        return self

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    # -- indexing --------------------------------------------------------
    def __getitem__(self, idx):
        from .. import ops
        return ops._getitem(self, idx)

    def __setitem__(self, idx, value):
        from .. import ops
        ops._setitem_inplace(self, idx, value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- operators (bound lazily to the ops registry) --------------------
    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={self._value.dtype}{grad_info},\n"
            f"       {np.asarray(self._value)!r})"
        )


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """Reference: paddle.to_tensor (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        v = data._value
    else:
        v = data
    d = dtypes.convert_dtype(dtype)
    if not isinstance(v, jax.Array):
        v = np.asarray(v)
        if d is None and v.dtype == np.float64:
            v = v.astype(np.float32)  # match reference default fp32
        if d is None and v.dtype == np.int64 and False:
            pass
        v = jnp.asarray(v, dtype=d)
    elif d is not None and v.dtype != d:
        v = v.astype(d)
    return Tensor(v, stop_gradient=stop_gradient)


def _bind_method(name, fn):
    """Attach an ops-registry function as a Tensor method."""
    if getattr(Tensor, name, None) is None or name not in Tensor.__slots__:
        try:
            setattr(Tensor, name, fn)
        except (AttributeError, TypeError):
            pass


def _tensor_from_numpy(arr, stop_gradient, name):
    """Unpickle helper (Tensor.__reduce__)."""
    import jax.numpy as jnp

    return Tensor(jnp.asarray(arr), stop_gradient=stop_gradient, name=name)
