"""paddle.Model — high-level train/eval/predict loops.

Reference analog: python/paddle/hapi/model.py (`Model.fit` :1054,
prepare/evaluate/predict/save/load, train_batch/eval_batch). TPU-native:
the loop stays in Python but every batch step runs through the eager tape
(or, when the user wraps the network with paddle_tpu.jit.to_static, one
compiled program per shape); callbacks/metrics accumulate on host.
"""
from __future__ import annotations

import os

import numpy as np

from .. import framework_io
from ..core.tensor import Tensor
from ..core.dispatch import no_grad
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import CallbackList, History, ProgBarLogger

__all__ = ["Model"]


def _to_tensor_list(data):
    if data is None:
        return []
    if isinstance(data, (list, tuple)):
        return [d if isinstance(d, Tensor) else Tensor(np.asarray(d))
                for d in data]
    return [data if isinstance(data, Tensor) else Tensor(np.asarray(data))]


def _mean_loss(loss):
    if isinstance(loss, (list, tuple)):
        total = loss[0]
        for l in loss[1:]:
            total = total + l
        return total
    return loss


class Model:
    """Wraps a Layer with fit/evaluate/predict (reference model.py:1054)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._save_dir = None

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, **kwargs):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        else:
            ms = metrics if isinstance(metrics, (list, tuple)) else [metrics]
            for m in ms:
                if not isinstance(m, Metric):
                    raise TypeError(f"metric {m!r} is not a Metric")
            self._metrics = list(ms)

    # -- single-batch ops (reference train_batch/eval_batch) ---------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = _to_tensor_list(inputs)
        lbs = _to_tensor_list(labels)
        outs = self.network(*ins)
        outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
        if self._loss is not None:
            loss = _mean_loss(self._loss(*(list(outs_list) + lbs)))
        else:
            loss = _mean_loss(outs)
        loss.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs_list, lbs)
        return ([float(loss)], metrics) if metrics else [float(loss)]

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = _to_tensor_list(inputs)
        lbs = _to_tensor_list(labels)
        outs = self.network(*ins)
        outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
        losses = []
        if self._loss is not None:
            losses = [float(_mean_loss(self._loss(*(list(outs_list) + lbs))))]
        metrics = self._update_metrics(outs_list, lbs)
        return (losses, metrics) if metrics else losses

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        outs = self.network(*_to_tensor_list(inputs))
        outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
        return [o.numpy() for o in outs_list]

    def _update_metrics(self, outs, labels):
        res = []
        for m in self._metrics:
            pre = m.compute(outs[0], *labels)
            pre = pre if isinstance(pre, (list, tuple)) else [pre]
            m.update(*pre)
            res.append(m.accumulate())
        return res

    def _metric_logs(self, logs):
        for m in self._metrics:
            names = m.name()
            vals = m.accumulate()
            if isinstance(names, str):
                names, vals = [names], [vals]
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            logs.update(dict(zip(names, vals)))
        return logs

    # -- loaders -----------------------------------------------------------
    def _loader(self, data, batch_size, shuffle, num_workers):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        return data  # any iterable of batches

    def _split_batch(self, batch):
        """(inputs, labels) from a loader batch. When the Model was built
        with inputs=/labels= specs (reference InputSpec lists), their arity
        drives the split; otherwise the last element is the label
        (reference convention for (image, label) datasets)."""
        if not isinstance(batch, (list, tuple)):
            return [batch], []
        if self._inputs is not None:
            n_in = len(self._inputs) \
                if isinstance(self._inputs, (list, tuple)) else 1
            return list(batch[:n_in]), list(batch[n_in:])
        if len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), []

    # -- loops -------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, shuffle=True, num_workers=0, callbacks=None,
            prefetch=0):
        """`prefetch=N` (N>=1) overlaps host->device transfer with compute:
        each epoch's loader is wrapped in
        `paddle_tpu.distributed.prefetch_to_device`, a bounded background
        thread that ships batches to the device N deep ahead of the train
        step (docs/performance.md)."""
        assert train_data is not None, "train_data is required"
        self._save_dir = save_dir
        loader = self._loader(train_data, batch_size, shuffle, num_workers)
        if epochs > 1 and iter(loader) is loader:
            raise ValueError(
                "train_data is a one-shot iterator and cannot be "
                "re-iterated for multiple epochs; pass a Dataset, "
                "DataLoader, or re-iterable of batches")
        eval_loader = self._loader(eval_data, batch_size, False, num_workers)
        cbs = [History(), ProgBarLogger(log_freq, verbose)]
        if save_dir:
            from .callbacks import ModelCheckpoint

            cbs.append(ModelCheckpoint(save_freq, save_dir))
        cbs += list(callbacks or [])
        steps = len(loader) if hasattr(loader, "__len__") else None
        cblist = CallbackList(cbs, model=self,
                              params={"epochs": epochs, "steps": steps,
                                      "verbose": verbose,
                                      "loader": loader})
        self.stop_training = False
        cblist.call("on_train_begin", {})
        # bit-exact data resume: ModelCheckpoint(auto_resume=True) leaves
        # the snapshotted data cursor on `_resume_data`; feeding it back
        # into the loader fast-forwards to the exact consumed position of
        # the interrupted run (same shuffle seed, same remaining batches)
        resume = getattr(self, "_resume_data", None)
        start_epoch = start_step = 0
        if resume is not None and hasattr(loader, "load_state_dict"):
            self._resume_data = None
            loader.load_state_dict(resume)
            start_epoch = int(resume.get("epoch", 0))
            start_step = int(resume.get("cursor", 0))
            if steps is not None and start_step >= steps:
                # checkpoint landed exactly on an epoch boundary — resume
                # at the top of the next epoch (same restored base_seed)
                start_epoch, start_step = start_epoch + 1, 0
                loader.load_state_dict(dict(resume, epoch=start_epoch,
                                            cursor=0))
        history = cbs[0]
        for epoch in range(start_epoch, epochs):
            cblist.call("on_epoch_begin", epoch, {})
            for m in self._metrics:
                m.reset()
            logs = {}
            if hasattr(loader, "set_epoch"):
                # epoch-pure shuffle order: f(base_seed, epoch) — the
                # anchor that makes mid-epoch resume bit-exact
                loader.set_epoch(epoch)
            batch_iter = loader
            if prefetch:
                from ..distributed.prefetch import prefetch_to_device

                batch_iter = prefetch_to_device(iter(loader), size=prefetch)
            try:
                for step, batch in enumerate(
                        batch_iter,
                        start=start_step if epoch == start_epoch else 0):
                    cblist.call("on_train_batch_begin", step, {})
                    ins, lbs = self._split_batch(batch)
                    res = self.train_batch(ins, lbs or None)
                    losses = res[0] if isinstance(res, tuple) else res
                    logs = self._metric_logs({"loss": losses[0]})
                    cblist.call("on_train_batch_end", step, logs)
                    if self.stop_training:
                        break
            finally:
                if prefetch:
                    batch_iter.close()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cblist)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cblist.call("on_epoch_end", epoch, logs)
            if self.stop_training:
                break
        cblist.call("on_train_end", {})
        return history.history

    def _run_eval(self, loader, cblist):
        for m in self._metrics:
            m.reset()
        cblist.call("on_eval_begin", {})
        logs = {}
        losses = []
        for step, batch in enumerate(loader):
            cblist.call("on_eval_batch_begin", step, {})
            ins, lbs = self._split_batch(batch)
            res = self.eval_batch(ins, lbs or None)
            ls = res[0] if isinstance(res, tuple) else res
            if ls:
                losses.append(ls[0])
            logs = self._metric_logs(
                {"loss": float(np.mean(losses))} if losses else {})
            cblist.call("on_eval_batch_end", step, logs)
        cblist.call("on_eval_end", logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._loader(eval_data, batch_size, False, num_workers)
        cblist = CallbackList(
            [ProgBarLogger(log_freq, verbose)] + list(callbacks or []),
            model=self, params={"verbose": verbose})
        return self._run_eval(loader, cblist)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None):
        loader = self._loader(test_data, batch_size, False, num_workers)
        cblist = CallbackList(list(callbacks or []), model=self)
        cblist.call("on_predict_begin", {})
        outputs = []
        for step, batch in enumerate(loader):
            ins, _ = self._split_batch(batch)
            cblist.call("on_predict_batch_begin", step, {})
            outs = self.predict_batch(ins)
            outputs.append(outs)
            cblist.call("on_predict_batch_end", step, {})
        cblist.call("on_predict_end", {})
        # regroup: list over outputs, each a list over batches
        n_out = len(outputs[0]) if outputs else 0
        grouped = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            grouped = [np.concatenate(g) for g in grouped]
        return grouped

    # -- persistence (reference: model.py save/load) -----------------------
    def save(self, path, training=True):
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        framework_io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework_io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        params = framework_io.load(path + ".pdparams")
        if skip_mismatch:
            current = self.network.state_dict()
            params = {k: v for k, v in params.items()
                      if k in current and
                      tuple(np.asarray(v).shape) == tuple(current[k].shape)}
        self.network.set_state_dict(params)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(framework_io.load(opt_path))

    def parameters(self):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary

        return summary(self.network, input_size, dtypes=dtype)
