"""paddle.summary (reference: python/paddle/hapi/model_summary.py) —
layer-by-layer table of output shapes and parameter counts via forward
hooks on a dry run."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import no_grad
from ..nn.layer.layers import Layer

__all__ = ["summary"]


def _num_params(layer):
    return sum(int(np.prod(p.shape)) for p in
               layer.parameters(include_sublayers=False))


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print and return {'total_params': N, 'trainable_params': N}."""
    rows = []
    hooks = []

    def mk_hook(name, layer):
        def hook(lyr, ins, out):
            o = out[0] if isinstance(out, (list, tuple)) else out
            shape = list(o.shape) if hasattr(o, "shape") else []
            rows.append((f"{type(layer).__name__}-{len(rows) + 1}",
                         name, shape, _num_params(layer)))
        return hook

    for name, sub in net.named_sublayers():
        if next(iter(sub.sublayers()), None) is None:  # leaves only
            hooks.append(sub.register_forward_post_hook(mk_hook(name, sub)))

    if input is None:
        if input_size is None:
            raise ValueError("either input_size or input is required")
        sizes = input_size if isinstance(input_size, list) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes] * len(sizes)
        input = [Tensor(np.zeros([d if d is not None and d > 0 else 1
                                  for d in s],
                                 dtype=np.dtype(dt or "float32")))
                 for s, dt in zip(sizes, dts)]
    elif not isinstance(input, (list, tuple)):
        input = [input]

    was_training = getattr(net, "training", True)
    net.eval()
    try:
        with no_grad():
            net(*input)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    w = max([len(r[0]) for r in rows] + [12]) + 2
    lines = [f"{'Layer (type)':<{w}} {'Output Shape':<24} {'Param #':>12}",
             "-" * (w + 38)]
    for cls_name, _, shape, n in rows:
        lines.append(f"{cls_name:<{w}} {str(shape):<24} {n:>12,}")
    lines += ["-" * (w + 38),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}"]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}
