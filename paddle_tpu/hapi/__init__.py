"""paddle_tpu.hapi — high-level Model API (reference: python/paddle/hapi/)."""
from .model import Model  # noqa: F401
from .model_summary import summary  # noqa: F401
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping,
    ReduceLROnPlateau, VisualDL, WandbCallback,
)
