"""hapi callbacks (reference: python/paddle/hapi/callbacks.py — Callback
base, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping,
VisualDL/WandB writers)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "History", "CallbackList"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = dict(params or {})

    # train/eval/predict lifecycle (reference callback surface)
    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def call(self, hook, *args):
        for c in self.callbacks:
            getattr(c, hook)(*args)

    def __iter__(self):
        return iter(self.callbacks)


class History(Callback):
    """Records per-epoch logs (always installed, like keras/hapi)."""

    def on_train_begin(self, logs=None):
        self.history = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ProgBarLogger(Callback):
    """Console logger (reference: callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = int(log_freq)
        self.verbose = int(verbose)

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.monotonic()

    def _fmt(self, logs):
        return " - ".join(
            f"{k}: {v:.4f}" if isinstance(v, (int, float, np.floating))
            else f"{k}: {v}" for k, v in (logs or {}).items())

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            print(f"Epoch {self.epoch + 1}/{self.epochs} "
                  f"step {step}/{self.steps} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            dt = time.monotonic() - self._t0
            print(f"Epoch {epoch + 1}/{self.epochs} [{dt:.1f}s] - "
                  f"{self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose >= 1:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    """Periodic save of model+optimizer state (reference: callbacks.py
    ModelCheckpoint: save_dir/{epoch} and final).

    Beyond the reference surface, this callback is the hapi entry into the
    fault-tolerant checkpoint subsystem (distributed/checkpoint): with
    `every_n_steps` set it snapshots model+optimizer+step through a
    `CheckpointManager` (crash-atomic commits, keep-last-K rotation,
    integrity manifest) under `<save_dir>/ckpt`, and with
    `auto_resume=True` it restores the newest committed snapshot at
    `on_train_begin` — the elastic relaunch path (launch/controller.py
    `--ckpt_dir`) supplies the snapshot root via PADDLE_TPU_CKPT_DIR (the
    env feeds only the manager; legacy per-epoch saves still require an
    explicit save_dir) so a restarted worker resumes instead of starting
    cold. The restored step
    is exposed as `self.resumed_step` and `model._resume_step`; the
    data-pipeline cursor (epoch, consumed-batch position, shuffle state —
    saved in the extra sidecar when `fit` hands the callback its loader)
    comes back as `self.resumed_data` / `model._resume_data`, which the
    fit loop feeds into `DataLoader.load_state_dict` so the relaunched
    run consumes the IDENTICAL remaining batch sequence
    (docs/checkpointing.md, "Self-healing training")."""

    def __init__(self, save_freq=1, save_dir=None, every_n_steps=None,
                 keep_last_k=3, auto_resume=False, async_save=False):
        super().__init__()
        self.save_freq = int(save_freq)
        self.save_dir = save_dir
        # the launcher's --ckpt_dir env fallback feeds ONLY the snapshot
        # manager; the legacy per-epoch full-model saves stay gated on an
        # explicitly passed save_dir
        self._ckpt_root = save_dir or os.environ.get("PADDLE_TPU_CKPT_DIR")
        self.every_n_steps = every_n_steps
        self.keep_last_k = int(keep_last_k)
        self.auto_resume = bool(auto_resume)
        if (every_n_steps or auto_resume) and not self._ckpt_root:
            raise ValueError(
                "ModelCheckpoint(every_n_steps=/auto_resume=) needs a "
                "checkpoint root: pass save_dir or launch with --ckpt_dir "
                "(PADDLE_TPU_CKPT_DIR)")
        self.async_save = bool(async_save)
        self._manager = None
        self._global_step = 0
        self._cur_epoch = 0
        self._epoch_step = 0     # CONSUMED batches this epoch (see _data_state)
        self.resumed_step = None
        self.resumed_data = None

    def _mgr(self):
        if self._manager is None:
            from ..distributed.checkpoint import CheckpointManager

            self._manager = CheckpointManager(
                os.path.join(self._ckpt_root, "ckpt"),
                keep_last_k=self.keep_last_k, async_save=self.async_save)
        return self._manager

    def _state(self, ensure_opt=False):
        """Snapshot tree: model + optimizer (+ the manager splits scalar
        leaves like `_step_count` into the extra sidecar)."""
        state = {"model": self.model.network.state_dict()}
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None:
            if ensure_opt:
                # materialize accumulators so the restore has targets even
                # before the first optimizer step of this incarnation
                opt._ensure_state(opt._parameter_list)
            state["opt"] = opt.state_dict()
        return state

    def _data_state(self):
        """Data-pipeline resume cursor for the checkpoint sidecar. Counts
        the batch position the TRAINING LOOP has consumed (`_epoch_step`),
        not the loader's produced cursor — with `fit(prefetch=)` the
        device queue runs ahead, and resuming at the produced position
        would silently drop the queued-but-unseen batches."""
        loader = self.params.get("loader")
        if loader is None or not hasattr(loader, "state_dict"):
            return None
        state = loader.state_dict(consumed=self._epoch_step)
        state["epoch"] = self._cur_epoch
        return state

    def _snapshot(self):
        extra = {"global_step": self._global_step}
        data = self._data_state()
        if data is not None:
            extra["data"] = data
        self._mgr().save(self._state(), step=self._global_step, extra=extra)

    def on_train_begin(self, logs=None):
        self._global_step = 0
        if not (self.auto_resume and self._ckpt_root and self.model):
            return
        state = self._state(ensure_opt=True)
        # strict=False: _ensure_state materializes accumulator targets for
        # EVERY param, but the snapshot only holds them for params that
        # had stepped by save time (frozen params have none) — those keep
        # their fresh zeros
        step = self._mgr().restore_latest(state, strict=False)
        if step is None:
            return
        self.model.network.set_state_dict(state["model"])
        opt = getattr(self.model, "_optimizer", None)
        if opt is not None and "opt" in state:
            opt.set_state_dict(state["opt"])
        self.resumed_step = step
        self.model._resume_step = step
        self._global_step = step  # keep step numbering monotonic
        # the data-pipeline cursor rides the extra sidecar; the fit loop
        # feeds it back into the loader for a bit-exact resume
        extra = self._mgr().last_extra or {}
        self.resumed_data = extra.get("data")
        self.model._resume_data = self.resumed_data

    def on_epoch_begin(self, epoch, logs=None):
        self._cur_epoch = epoch
        self._epoch_step = 0

    def on_train_batch_end(self, step, logs=None):
        self._epoch_step = step + 1
        self._global_step += 1
        if self.every_n_steps and self._ckpt_root and self.model and \
                self._global_step % int(self.every_n_steps) == 0:
            self._snapshot()

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self._manager is not None:
            self._manager.wait()  # surface async IO errors before exit
        if self.save_dir and self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference: callbacks.py
    LRScheduler — by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("choose one of by_step / by_epoch")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference:
    callbacks.py EarlyStopping)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, min_delta=0,
                 baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = int(patience)
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.reset()

    def reset(self):
        self.wait = 0
        self.stopped_epoch = 0
        self.best = self.baseline if self.baseline is not None else (
            -np.inf if self.mode == "max" else np.inf)

    def _better(self, cur):
        if self.mode == "max":
            return cur > self.best + self.min_delta
        return cur < self.best - self.min_delta

    def on_train_begin(self, logs=None):
        self.reset()

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple, np.ndarray)):
            cur = float(np.asarray(cur).reshape(-1)[0])
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.model is not None and \
                    getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class ReduceLROnPlateau(Callback):
    """Reduce model LR when a monitored metric stalls (reference:
    hapi/callbacks.py:1172)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 verbose=1, mode="auto", min_delta=1e-4, cooldown=0,
                 min_lr=0.0):
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.mode = "min" if mode == "auto" else mode
        self.best = None
        self.cooldown_counter = 0
        self.num_bad_epochs = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple)) else cur)
        better = (self.best is None
                  or (self.mode == "min"
                      and cur < self.best - self.min_delta)
                  or (self.mode == "max"
                      and cur > self.best + self.min_delta))
        if better:
            self.best = cur
            self.num_bad_epochs = 0
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            return
        self.num_bad_epochs += 1
        if self.num_bad_epochs > self.patience:
            opt = getattr(self.model, "_optimizer", None)
            if opt is not None:
                from ..optimizer.lr import LRScheduler
                lr = opt.get_lr() if hasattr(opt, "get_lr") else None
                if lr is not None:
                    new_lr = max(lr * self.factor, self.min_lr)
                    if hasattr(opt, "set_lr"):
                        opt.set_lr(new_lr)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr -> {new_lr:.3e}")
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0


class VisualDL(Callback):
    """Scalar logging callback (reference: hapi/callbacks.py:883 writes
    VisualDL records). The visualdl package is not vendored (zero
    egress); scalars append to a plain JSONL the reference UI can
    ingest offline."""

    def __init__(self, log_dir="./log"):
        import os
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None
        self._step = 0

    def _write(self, tag, logs):
        import json
        import os
        if self._f is None:
            self._f = open(os.path.join(self.log_dir, "scalars.jsonl"),
                           "a")
        for k, v in (logs or {}).items():
            try:
                v = float(v[0] if isinstance(v, (list, tuple)) else v)
            except (TypeError, ValueError):
                continue
            self._f.write(json.dumps({"tag": f"{tag}/{k}",
                                      "step": self._step,
                                      "value": v}) + "\n")
        self._f.flush()

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self._step % 10 == 0:
            self._write("train", logs)

    def on_eval_end(self, logs=None):
        self._write("eval", logs)

    def on_train_end(self, logs=None):
        if self._f is not None:
            self._f.close()
            self._f = None


class WandbCallback(Callback):
    """Weights&Biases logging (reference: hapi/callbacks.py:999). wandb is
    not vendored (zero egress): with the package absent this raises at
    construction, matching the reference's `ModuleNotFoundError` path."""

    def __init__(self, project=None, **kwargs):
        try:
            import wandb  # noqa: F401
        except ImportError as e:
            raise ModuleNotFoundError(
                "WandbCallback requires the wandb package, which is not "
                "available in this environment (zero egress)") from e
