"""Model FLOPs counter (reference: python/paddle/hapi/dynamic_flops.py
flops() — walks the layer tree with forward hooks and per-layer-type
counting rules)."""
from __future__ import annotations

import numpy as np

from .. import nn

__all__ = ["flops"]


def _count_linear(layer, x, y):
    return int(np.prod(layer.weight.shape))


def _count_conv(layer, x, y):
    # output elements * (kernel volume * in_channels / groups)
    w = layer.weight
    out_elems = int(np.prod(y.shape[1:]))
    kernel = int(np.prod(w.shape[1:]))      # Cin/g * prod(k)
    return out_elems * kernel


def _count_norm(layer, x, y):
    return 2 * int(np.prod(x.shape[1:]))


def _count_act(layer, x, y):
    return int(np.prod(x.shape[1:]))


_RULES = [
    (nn.Conv1D, _count_conv), (nn.Conv2D, _count_conv),
    (nn.Conv3D, _count_conv), (nn.Linear, _count_linear),
    (nn.BatchNorm1D, _count_norm), (nn.BatchNorm2D, _count_norm),
    (nn.BatchNorm3D, _count_norm), (nn.LayerNorm, _count_norm),
    (nn.ReLU, _count_act), (nn.GELU, _count_act), (nn.Sigmoid, _count_act),
]


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Count multiply-accumulates of one forward pass (reference:
    hapi/dynamic_flops.py flops; same per-layer rule style, batch dim of
    input_size treated as given)."""
    import paddle_tpu as paddle

    rules = list(_RULES)
    if custom_ops:
        rules = [(k, v) for k, v in custom_ops.items()] + rules

    totals = {}
    hooks = []

    def make_hook(name, layer, fn):
        def hook(lyr, inputs, output):
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            y = output[0] if isinstance(output, (tuple, list)) else output
            totals[name] = totals.get(name, 0) + int(fn(lyr, x, y))
        return hook

    for name, sub in net.named_sublayers():
        for cls, fn in rules:
            if type(sub) is cls or (custom_ops and type(sub) in
                                    (custom_ops or {})):
                hooks.append(sub.register_forward_post_hook(
                    make_hook(name or type(sub).__name__, sub, fn)))
                break

    was_training = net.training
    net.eval()
    x = paddle.zeros(list(input_size))
    try:
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(totals.values())
    if print_detail:
        for k, v in sorted(totals.items(), key=lambda kv: -kv[1]):
            print(f"{k:40s} {v:>14,d}")
        print(f"{'TOTAL (MACs)':40s} {total:>14,d}")
    return total
