"""paddle_tpu.quantization — QAT (fake-quant) and PTQ.

Reference analog: python/paddle/quantization/ (QuantConfig config.py, QAT
qat.py wrapping layers with quanted counterparts, PTQ ptq.py with
observers) and the imperative qat pass. TPU-native: fake-quant is pure
jnp math with a straight-through estimator, so QAT graphs stay fully
jittable; "convert" bakes int8 weights + scales for a simulated-int8
deploy path (XLA int8 matmul).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import apply
from .. import nn

__all__ = [
    "BaseQuanter", "BaseObserver", "quanter",
    "fake_quant", "quant_linear", "dequant_linear",
    "AbsmaxObserver", "MovingAverageAbsmaxObserver", "PerChannelAbsmaxObserver",
    "QuantConfig", "QAT", "PTQ", "QuantedLinear", "QuantedConv2D",
]


# ---- functional -----------------------------------------------------------

def _fake_quant_impl(x, scale, *, bits):
    qmax = 2.0 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) * s / qmax
    # straight-through estimator: identity gradient
    return x + jax.lax.stop_gradient(q - x)


def fake_quant(x, scale, bits=8):
    """Simulated quantization with STE gradients (reference:
    fake_quantize_abs_max op)."""
    return apply("fake_quant", _fake_quant_impl, [x, scale], {"bits": bits})


def quant_linear(x, scale, bits=8):
    """float -> int8 values (deploy path)."""
    qmax = 2.0 ** (bits - 1) - 1
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    s = jnp.maximum(jnp.asarray(scale), 1e-9)
    return Tensor(jnp.clip(jnp.round(v / s * qmax), -qmax, qmax)
                  .astype(jnp.int8))


def dequant_linear(q, scale, bits=8):
    qmax = 2.0 ** (bits - 1) - 1
    v = q._value if isinstance(q, Tensor) else jnp.asarray(q)
    return Tensor(v.astype(jnp.float32) * jnp.asarray(scale) / qmax)


# ---- observers ------------------------------------------------------------

class AbsmaxObserver:
    """Running max(|x|) (reference: AbsmaxObserver observers/abs_max.py)."""

    def __init__(self, quant_bits=8):
        self.quant_bits = quant_bits
        self._max = 0.0

    def observe(self, x):
        v = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
        self._max = max(self._max, float(np.abs(v).max(initial=0.0)))

    def scale(self):
        return self._max if self._max > 0 else 1e-9


class MovingAverageAbsmaxObserver(AbsmaxObserver):
    """EMA of abs-max (reference: moving_average_abs_max)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.rate = moving_rate
        self._ema = None

    def observe(self, x):
        v = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
        cur = float(np.abs(v).max(initial=0.0))
        self._ema = cur if self._ema is None else \
            self.rate * self._ema + (1 - self.rate) * cur

    def scale(self):
        return self._ema if self._ema else 1e-9


class PerChannelAbsmaxObserver:
    """Per-output-channel abs-max for weights (reference:
    channel_wise_abs_max)."""

    def __init__(self, quant_bits=8, axis=0):
        self.quant_bits = quant_bits
        self.axis = axis
        self._max = None

    def observe(self, x):
        v = x.numpy() if isinstance(x, Tensor) else np.asarray(x)
        red = tuple(i for i in range(v.ndim) if i != self.axis)
        cur = np.abs(v).max(axis=red)
        self._max = cur if self._max is None else np.maximum(self._max, cur)

    def scale(self):
        if self._max is None:
            return np.asarray(1e-9)
        return np.maximum(self._max, 1e-9)


# ---- config + quanted layers ---------------------------------------------

class QuantConfig:
    """Reference: quantization/config.py — which layers get quantized and
    with what observers."""

    def __init__(self, activation=None, weight=None, weight_bits=8,
                 activation_bits=8):
        self.activation_factory = activation or MovingAverageAbsmaxObserver
        self.weight_factory = weight or AbsmaxObserver
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.types = (nn.Linear, nn.Conv2D)


def _broadcast_scale(scale, ndim, axis):
    """Per-channel scale vector -> shape broadcastable against a weight of
    rank `ndim` along its observed `axis`; scalars pass through."""
    scale = np.asarray(scale, np.float32)
    if scale.ndim == 1:
        bshape = [1] * ndim
        bshape[axis] = scale.shape[0]
        return scale.reshape(bshape)
    return scale


class _QuantedBase(nn.Layer):
    def __init__(self, layer, cfg: QuantConfig):
        super().__init__()
        self.inner = layer
        self.cfg = cfg
        self.w_observer = cfg.weight_factory(cfg.weight_bits)
        self.a_observer = cfg.activation_factory(cfg.activation_bits)

    @staticmethod
    def _concrete(t):
        import jax

        v = t._value if isinstance(t, Tensor) else t
        return not isinstance(v, jax.core.Tracer)

    def forward(self, x):
        # observers pull values to host — skip under tracing (jit.save /
        # user-jitted steps run with the last calibrated scales frozen)
        if self._concrete(x):
            self.a_observer.observe(x)
        a_scale = Tensor(np.float32(self.a_observer.scale()))
        xq = fake_quant(x, a_scale, self.cfg.activation_bits)
        w = self.inner.weight
        if self._concrete(w):
            self.w_observer.observe(w)
        w_scale = Tensor(_broadcast_scale(
            self.w_observer.scale(), w.ndim,
            getattr(self.w_observer, "axis", 0)))
        wq = fake_quant(w, w_scale, self.cfg.weight_bits)
        return self._call_inner(xq, wq)


class QuantedLinear(_QuantedBase):
    def _call_inner(self, x, w):
        from ..nn import functional as F

        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(_QuantedBase):
    def _call_inner(self, x, w):
        from ..nn import functional as F

        inner = self.inner
        return F.conv2d(x, w, inner.bias, stride=inner._stride,
                        padding=inner._padding, dilation=inner._dilation,
                        groups=inner._groups)


class _ConvertedBase(nn.Layer):
    """Inference-time quantized layer: int8 weight buffer + frozen scales
    (the runtime form the reference's convert pass emits)."""

    def __init__(self, quanted: "_QuantedBase", cfg: "QuantConfig"):
        super().__init__()
        inner = quanted.inner
        self.bits = cfg.weight_bits
        self.act_bits = cfg.activation_bits
        w_scale = _broadcast_scale(quanted.w_observer.scale(),
                                   inner.weight.ndim,
                                   getattr(quanted.w_observer, "axis", 0))
        # registered buffers: state_dict/save must carry the deploy-form
        # weights (int8 + scales), not silently drop them
        self.register_buffer("weight_scale", Tensor(w_scale))
        self.register_buffer(
            "act_scale", Tensor(np.float32(quanted.a_observer.scale())))
        wq = quant_linear(inner.weight, Tensor(w_scale), self.bits)
        self.register_buffer("weight_int8",
                             Tensor(wq._value.astype("int8")))
        self.bias = getattr(inner, "bias", None)
        # copy the hyperparameters and DROP the fp32 layer — keeping it
        # registered would retain (and serialize) the weights this pass
        # exists to shrink
        if isinstance(inner, nn.Conv2D):
            self._stride = inner._stride
            self._padding = inner._padding
            self._dilation = inner._dilation
            self._groups = inner._groups

    def _dequant_weight(self):
        from .. import ops
        w = ops.cast(self.weight_int8, "float32")
        scale = Tensor(self.weight_scale)  # broadcasts per-channel scales
        return w * scale / float(2 ** (self.bits - 1) - 1)


class ConvertedLinear(_ConvertedBase):
    def forward(self, x):
        from ..nn import functional as F
        xq = fake_quant(x, self.act_scale, self.act_bits)
        return F.linear(xq, self._dequant_weight(), self.bias)


class ConvertedConv2D(_ConvertedBase):
    def forward(self, x):
        from ..nn import functional as F
        xq = fake_quant(x, self.act_scale, self.act_bits)
        return F.conv2d(xq, self._dequant_weight(), self.bias,
                        stride=self._stride, padding=self._padding,
                        dilation=self._dilation, groups=self._groups)


# ---- QAT / PTQ drivers ----------------------------------------------------

def _swap_layers(model, cfg, wrap):
    for name, sub in list(model.named_sublayers()):
        parent = model
        parts = name.split(".")
        for p in parts[:-1]:
            parent = getattr(parent, p)
        if isinstance(sub, cfg.types) and not isinstance(sub, _QuantedBase):
            wrapped = wrap(sub)
            setattr(parent, parts[-1], wrapped)
    return model


class QAT:
    """Quantization-aware training (reference: qat.py QAT.quantize)."""

    def __init__(self, config: QuantConfig | None = None):
        self.cfg = config or QuantConfig()

    def quantize(self, model, inplace=True):
        def wrap(layer):
            if isinstance(layer, nn.Conv2D):
                return QuantedConv2D(layer, self.cfg)
            return QuantedLinear(layer, self.cfg)

        return _swap_layers(model, self.cfg, wrap)

    def convert(self, model, inplace=True):
        """Conversion pass (reference: quantization/quantize.py convert →
        inference program with frozen quant scales): every _QuantedBase
        wrapper is REPLACED by a Converted* inference layer holding the
        int8 weight buffer + frozen weight/activation scales — observers
        are gone, weight memory is 1/4, and the dequant folds into the
        matmul/conv under XLA fusion."""
        for name, sub in list(model.named_sublayers()):
            if not isinstance(sub, _QuantedBase):
                continue
            parent = model
            parts = name.split(".")
            for p in parts[:-1]:
                parent = getattr(parent, p)
            cls = (ConvertedConv2D if isinstance(sub, QuantedConv2D)
                   else ConvertedLinear)
            setattr(parent, parts[-1], cls(sub, self.cfg))
        return model


class PTQ:
    """Post-training quantization (reference: ptq.py): wrap, run calib
    batches, convert."""

    def __init__(self, config: QuantConfig | None = None):
        self.cfg = config or QuantConfig(
            activation=MovingAverageAbsmaxObserver)
        self._qat = QAT(self.cfg)

    def quantize(self, model, inplace=True):
        return self._qat.quantize(model, inplace)

    def convert(self, model, inplace=True):
        return self._qat.convert(model, inplace)


class BaseQuanter:
    """Reference: quantization/factory.py BaseQuanter — the trainable
    fake-quant node interface QAT layers call."""

    def __call__(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError


class BaseObserver(BaseQuanter):
    """Reference: quantization/factory.py BaseObserver — a quanter that
    only collects statistics (PTQ calibration)."""


def quanter(class_name):
    """Reference: quantization/factory.py quanter decorator — registers a
    Quanter config class for a BaseQuanter implementation."""
    def decorator(cls):
        import sys
        mod = sys.modules[__name__]

        class _Config:
            def __init__(self, *args, **kwargs):
                self._args = args
                self._kwargs = kwargs

            def _instance(self, layer=None):
                return cls(*self._args, **self._kwargs)

        _Config.__name__ = class_name
        setattr(mod, class_name, _Config)
        return cls
    return decorator
