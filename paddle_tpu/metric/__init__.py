"""paddle_tpu.metric — streaming evaluation metrics.

Reference analog: python/paddle/metric/metrics.py (Metric base with
update/accumulate/reset/name, Accuracy, Precision, Recall, Auc). Metrics
accumulate on host in numpy — they sit outside the jitted step, exactly
like the reference keeps them outside the Program.
"""
from __future__ import annotations

import abc

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: metric/metrics.py accuracy)."""
    import numpy as _np
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    logits = _np.asarray(input._value if isinstance(input, Tensor)
                         else input)
    lab = _np.asarray(label._value if isinstance(label, Tensor)
                      else label).reshape(-1)
    topk = _np.argsort(-logits, axis=-1)[:, :k]
    hit = (topk == lab[:, None]).any(axis=1)
    return Tensor(_jnp.asarray(_np.float32(hit.mean())))


def _to_numpy(x):
    if hasattr(x, "numpy"):
        return np.asarray(x.numpy())
    return np.asarray(x)


class Metric(abc.ABC):
    """Base metric (reference: metrics.py Metric)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, pred, label, *args):
        """Optional pre-processing run on device outputs; default passthrough
        (reference: Metric.compute)."""
        return pred, label


class Accuracy(Metric):
    """Top-k accuracy (reference: metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = _to_numpy(pred)
        label = _to_numpy(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:  # one-hot or [N, 1] label
            if label.shape[-1] == pred.shape[-1] and pred.shape[-1] > 1:
                label = label.argmax(-1)
            else:
                label = label.squeeze(-1)
        correct = (idx == label[..., None]).astype(np.float32)
        return correct

    def update(self, correct, *args):
        correct = _to_numpy(correct)
        n = correct[..., 0].size
        for i, k in enumerate(self.topk):
            self.total[i] += correct[..., :k].sum()
            self.count[i] += n
        res = self.total / np.maximum(self.count, 1)
        return res[0] if len(self.topk) == 1 else res

    def accumulate(self):
        res = self.total / np.maximum(self.count, 1)
        return float(res[0]) if len(self.topk) == 1 else [float(r) for r in res]

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision over probability predictions (reference:
    metrics.py Precision — label 0/1, pred thresholded at 0.5)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(_to_numpy(preds)).astype(np.int64).reshape(-1)
        labels = _to_numpy(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference: metrics.py Recall)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(_to_numpy(preds)).astype(np.int64).reshape(-1)
        labels = _to_numpy(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via thresholded histogram accumulation (reference:
    metrics.py Auc — same bucketed trapezoid estimate)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = int(num_thresholds)
        self._name = name
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = _to_numpy(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]  # P(class=1), reference convention
        preds = preds.reshape(-1)
        labels = _to_numpy(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels != 1], 1)

    def accumulate(self):
        # sweep thresholds high->low accumulating TP/FP counts
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        # anchor at (0, 0): without it the first trapezoid is dropped and a
        # single-bucket distribution degenerates to 0 instead of 0.5
        tpr = np.concatenate([[0.0], tp / tot_pos])
        fpr = np.concatenate([[0.0], fp / tot_neg])
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name
