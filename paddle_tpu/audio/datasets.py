"""Audio classification datasets (reference: python/paddle/audio/datasets/
— AudioClassificationDataset base, TESS, ESC50).

Zero-egress contract (same as text/vision datasets): pass the local archive
the reference would have downloaded, or synthetic=N for a schema-compatible
random dataset; download=True raises with instructions.
"""
from __future__ import annotations

import os
import zipfile

import numpy as np

from ..io import Dataset

__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]


def _no_download(name):
    raise NotImplementedError(
        f"{name}: automatic download is unavailable in this environment "
        f"(zero egress). Pass archive_path= pointing at the reference's "
        f"cached archive, or synthetic=N for a schema-compatible random "
        f"dataset.")


class AudioClassificationDataset(Dataset):
    """Base: (waveform-or-feature, label) records (reference:
    audio/datasets/dataset.py:29). feat_type 'raw' returns the waveform;
    'mfcc'/'melspectrogram'/'logmelspectrogram'/'spectrogram' run the
    corresponding feature layer from paddle_tpu.audio.features."""

    def __init__(self, files=None, labels=None, waveforms=None,
                 feat_type="raw", sample_rate=16000, **feat_config):
        super().__init__()
        known = ("raw", "mfcc", "melspectrogram", "logmelspectrogram",
                 "spectrogram")
        if feat_type not in known:
            raise RuntimeError(
                f"Unknown feat_type: {feat_type}, it must be one in "
                f"{list(known)}")
        self.files = files or []
        self.labels = labels or []
        self.waveforms = waveforms          # optional in-memory samples
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = feat_config
        self._feat_layer = None

    def _waveform(self, idx):
        if self.waveforms is not None:
            return self.waveforms[idx]
        from .backends import load
        wav, sr = load(self.files[idx])
        w = wav.numpy()
        return w[0] if w.ndim == 2 else w

    def _features(self, wave_np):
        if self.feat_type == "raw":
            return wave_np.astype("float32")
        if self._feat_layer is None:
            from . import features as feat_mod
            cls = {"mfcc": feat_mod.MFCC,
                   "melspectrogram": feat_mod.MelSpectrogram,
                   "logmelspectrogram": feat_mod.LogMelSpectrogram,
                   "spectrogram": feat_mod.Spectrogram}[self.feat_type]
            self._feat_layer = cls(sr=self.sample_rate, **self.feat_config) \
                if self.feat_type != "spectrogram" \
                else cls(**self.feat_config)
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        out = self._feat_layer(Tensor(jnp.asarray(wave_np[None])))
        return np.asarray(out._value)[0]

    def __getitem__(self, idx):
        feat = self._features(self._waveform(idx))
        return feat, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.waveforms if self.waveforms is not None
                   else self.files)


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set (reference: audio/datasets/tess.py) —
    7 emotion classes, n-fold split by speaker/word hash."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral", "ps",
                  "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 archive_path=None, download=False, synthetic=0, seed=0,
                 sample_rate=16000, **kw):
        assert mode in ("train", "dev")
        assert 1 <= split <= n_folds
        if synthetic:
            waves, labels = _synth_audio(int(synthetic), len(
                self.label_list), seed, sample_rate)
            super().__init__(waveforms=waves, labels=labels,
                             feat_type=feat_type, sample_rate=sample_rate,
                             **kw)
            return
        if archive_path:
            files, labels = self._load_archive(archive_path, mode, n_folds,
                                               split)
            super().__init__(files=files, labels=labels,
                             feat_type=feat_type, sample_rate=sample_rate,
                             **kw)
            return
        if download:
            _no_download("TESS")
        raise ValueError("pass archive_path=, or synthetic=N")

    def _load_archive(self, archive_path, mode, n_folds, split):
        root = os.path.dirname(os.path.abspath(archive_path))
        with zipfile.ZipFile(archive_path) as zf:
            names = [n for n in zf.namelist() if n.endswith(".wav")]
            zf.extractall(root)
        files, labels = [], []
        for i, n in enumerate(sorted(names)):
            emotion = os.path.basename(n).split("_")[-1][:-4].lower()
            if emotion not in self.label_list:
                continue
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                files.append(os.path.join(root, n))
                labels.append(self.label_list.index(emotion))
        return files, labels


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference: audio/datasets/esc50.py) —
    50 classes, 5 predefined folds from the meta csv."""

    n_class = 50

    def __init__(self, mode="train", split=1, feat_type="raw",
                 archive_path=None, download=False, synthetic=0, seed=0,
                 sample_rate=44100, **kw):
        assert mode in ("train", "dev")
        if synthetic:
            waves, labels = _synth_audio(int(synthetic), self.n_class,
                                         seed, sample_rate)
            super().__init__(waveforms=waves, labels=labels,
                             feat_type=feat_type, sample_rate=sample_rate,
                             **kw)
            return
        if archive_path:
            files, labels = self._load_archive(archive_path, mode, split)
            super().__init__(files=files, labels=labels,
                             feat_type=feat_type, sample_rate=sample_rate,
                             **kw)
            return
        if download:
            _no_download("ESC50")
        raise ValueError("pass archive_path=, or synthetic=N")

    def _load_archive(self, archive_path, mode, split):
        root = os.path.dirname(os.path.abspath(archive_path))
        with zipfile.ZipFile(archive_path) as zf:
            zf.extractall(root)
            meta = [n for n in zf.namelist() if n.endswith("esc50.csv")]
            audio_names = {os.path.basename(n): n for n in zf.namelist()
                           if n.endswith(".wav")}
        files, labels = [], []
        with open(os.path.join(root, meta[0])) as f:
            header = f.readline().strip().split(",")
            fi = {k: i for i, k in enumerate(header)}
            for line in f:
                row = line.strip().split(",")
                fold = int(row[fi["fold"]])
                keep = (fold != split) if mode == "train" \
                    else (fold == split)
                if keep and row[fi["filename"]] in audio_names:
                    files.append(os.path.join(
                        root, audio_names[row[fi["filename"]]]))
                    labels.append(int(row[fi["target"]]))
        return files, labels


def _synth_audio(n, n_class, seed, sample_rate):
    rng = np.random.RandomState(seed)
    waves, labels = [], []
    for _ in range(n):
        dur = sample_rate // 10            # 100 ms clips
        t = np.arange(dur) / sample_rate
        f0 = rng.uniform(100, 2000)
        w = (0.3 * np.sin(2 * np.pi * f0 * t)
             + 0.05 * rng.standard_normal(dur)).astype("float32")
        waves.append(w)
        labels.append(int(rng.randint(0, n_class)))
    return waves, labels
