"""Audio IO backend (reference: python/paddle/audio/backends/
wave_backend.py — the stdlib-`wave` backend paddle ships when paddleaudio
is absent; same load/save/info surface).

Zero-egress TPU build: PCM WAV via the stdlib, float32 normalization
matching the reference (int PCM scaled to [-1, 1])."""
from __future__ import annotations

import wave

import numpy as np

from ..core.tensor import Tensor

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


class AudioInfo:
    """Signal metadata (reference: backends/backend.py AudioInfo)."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding="PCM_S"):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):
        return (f"AudioInfo(sample_rate={self.sample_rate}, "
                f"num_samples={self.num_samples}, "
                f"num_channels={self.num_channels}, "
                f"bits_per_sample={self.bits_per_sample})")


def info(filepath):
    """Reference: wave_backend.info."""
    with wave.open(str(filepath), "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8)


_PCM_DTYPE = {1: np.uint8, 2: np.int16, 4: np.int32}


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Read a PCM WAV file -> (Tensor [C, T] float32 in [-1, 1], sr)
    (reference: wave_backend.load)."""
    with wave.open(str(filepath), "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(int(frame_offset))
        n = f.getnframes() - int(frame_offset) if num_frames < 0 \
            else int(num_frames)
        raw = f.readframes(n)
    dt = _PCM_DTYPE.get(width)
    if dt is None:
        raise ValueError(f"unsupported PCM sample width {width}")
    data = np.frombuffer(raw, dt).reshape(-1, nch)
    if width == 1:                       # unsigned 8-bit
        x = (data.astype(np.float32) - 128.0) / 128.0
    else:
        x = data.astype(np.float32) / float(1 << (8 * width - 1))
    if not normalize:
        x = data.astype(np.float32)
    wavef = x.T if channels_first else x
    return Tensor(wavef), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Write float32 [-1, 1] samples as PCM WAV (reference:
    wave_backend.save)."""
    x = np.asarray(src.numpy() if isinstance(src, Tensor) else src,
                   np.float32)
    if channels_first:
        x = x.T                            # -> [T, C]
    if x.ndim == 1:
        x = x[:, None]
    if bits_per_sample != 16:
        raise ValueError("wave backend writes PCM_16 only "
                         "(reference wave_backend.save:203 same limit)")
    pcm = np.clip(x, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype(np.int16)
    with wave.open(str(filepath), "wb") as f:
        f.setnchannels(x.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"backend {backend_name!r}: only the stdlib wave backend ships "
            "in this zero-egress build (the reference falls back to the "
            "same backend without paddleaudio)")
