"""paddle_tpu.audio (reference: python/paddle/audio/ — functional mel/
spectrogram features, feature layers, wave IO backend, datasets)."""
from . import functional  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .features import Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC  # noqa: F401
from .backends import load, save, info  # noqa: F401
