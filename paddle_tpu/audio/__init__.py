"""paddle_tpu.audio (reference: python/paddle/audio/ — functional mel/
spectrogram features + feature layers)."""
from . import functional  # noqa: F401
from .features import Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC  # noqa: F401
