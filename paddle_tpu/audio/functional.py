"""Audio functional ops (reference: python/paddle/audio/functional/ —
hz<->mel conversion, mel filterbanks, window functions)."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["hz_to_mel", "mel_to_hz", "compute_fbank_matrix", "get_window",
           "power_to_db", "create_dct"]


def hz_to_mel(freq, htk=False):
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    # slaney scale (reference default)
    f = np.asarray(freq, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(f >= min_log_hz,
                    min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz)
                    / logstep, mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    m = np.asarray(mel, np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(m >= min_log_mel,
                    min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, n_fft//2+1] triangular mel filterbank (reference
    functional.compute_fbank_matrix)."""
    f_max = f_max or sr / 2
    fft_freqs = np.linspace(0, sr / 2, n_fft // 2 + 1)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, len(fft_freqs)))
    for m in range(n_mels):
        lo, ctr, hi = hz_pts[m], hz_pts[m + 1], hz_pts[m + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[m] = np.maximum(0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(dtype)))


def get_window(window, win_length, fftbins=True, dtype="float32"):
    n = int(win_length)
    x = np.arange(n)
    denom = n if fftbins else n - 1
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * x / denom)
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * x / denom)
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * x / denom)
             + 0.08 * np.cos(4 * np.pi * x / denom))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))


def power_to_db(magnitude, ref_value=1.0, amin=1e-10, top_db=80.0):
    x = magnitude._value if isinstance(magnitude, Tensor) else \
        jnp.asarray(magnitude)
    db = 10.0 * jnp.log10(jnp.maximum(x, amin))
    db -= 10.0 * math.log10(max(ref_value, amin))
    if top_db is not None:
        db = jnp.maximum(db, db.max() - top_db)
    return Tensor(db)


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II matrix (reference functional.create_dct)."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    dct = np.cos(np.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / np.sqrt(2)
        dct *= np.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.astype(dtype)))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """Mel-spaced frequency bin centers (reference:
    python/paddle/audio/functional/functional.py mel_frequencies)."""
    mmin = hz_to_mel(f_min, htk=htk)
    mmax = hz_to_mel(f_max, htk=htk)
    mels = jnp.linspace(float(mmin), float(mmax), n_mels)
    return Tensor(jnp.asarray(
        [float(mel_to_hz(float(m), htk=htk)) for m in mels],
        _np_dtype(dtype)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """FFT bin center frequencies (reference: audio/functional/functional.py
    fft_frequencies)."""
    return Tensor(jnp.linspace(0.0, float(sr) / 2, 1 + n_fft // 2).astype(
        _np_dtype(dtype)))


def _np_dtype(dtype):
    from ..core.dtype import convert_dtype
    return convert_dtype(dtype)
