"""Audio feature layers (reference: python/paddle/audio/features/layers.py
— Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)."""
from __future__ import annotations

from .. import signal as _signal
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import functional as AF


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length, self.win_length,
                            window=self.window, center=self.center,
                            pad_mode=self.pad_mode)
        return spec.abs() ** self.power


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                             htk, norm, dtype)

    def forward(self, x):
        spec = self.spectrogram(x)  # [..., freq, frames]
        return self.fbank @ spec


class LogMelSpectrogram(Layer):
    def __init__(self, *args, ref_value=1.0, amin=1e-10, top_db=None,
                 **kwargs):
        super().__init__()
        self.mel = MelSpectrogram(*args, **kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_mels=64, **mel_kwargs):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr=sr, n_mels=n_mels, **mel_kwargs)
        self.dct = AF.create_dct(n_mfcc, n_mels)

    def forward(self, x):
        m = self.logmel(x)  # [..., n_mels, frames]
        return self.dct.t() @ m
