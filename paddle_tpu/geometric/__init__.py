"""paddle.geometric parity — graph segment ops, message passing, reindex,
sampling.

Reference: python/paddle/geometric/ (segment kernels
phi/kernels/gpu/segment_pool_kernel.cu, graph_send_recv kernels
phi/kernels/gpu/graph_send_recv_kernel.cu). TPU design: everything is a
`jax.ops.segment_*` reduction — one XLA scatter per op, which Mosaic lowers
to an efficient sorted-segment loop; no custom kernel needed. Neighbor
sampling is host-side (data-dependent shapes don't jit) like the
reference's CPU sampling kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..ops._helpers import apply, wrap, Tensor

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv",
    "reindex_graph", "reindex_heter_graph",
    "sample_neighbors", "weighted_sample_neighbors",
    "Graph",
]


# ---------------------------------------------------------------------------
# segment reductions (geometric/math.py)
# ---------------------------------------------------------------------------

def _seg_n(ids):
    return int(np.asarray(ids if not isinstance(ids, Tensor)
                          else ids._value).max()) + 1 if (
        np.asarray(ids if not isinstance(ids, Tensor)
                   else ids._value).size) else 0


def _segment_factory(name, jfn, empty_fill):
    def impl(data, ids, *, n):
        out = jfn(data, ids, num_segments=n)
        if empty_fill is not None:
            # segments with no members: reference fills 0
            counts = jax.ops.segment_sum(jnp.ones_like(ids, jnp.int32), ids,
                                         num_segments=n)
            shape = (n,) + (1,) * (data.ndim - 1)
            out = jnp.where(counts.reshape(shape) > 0, out, empty_fill)
        return out

    impl.__name__ = f"_{name}_impl"

    def op(data, segment_ids, name=None):
        data, segment_ids = wrap(data), wrap(segment_ids)
        return apply(_n, impl, (data, segment_ids),
                     {"n": _seg_n(segment_ids)})

    _n = name
    op.__name__ = name
    op.__doc__ = (f"Segment {name.split('_')[1]} over the leading dim "
                  f"(reference: python/paddle/geometric/math.py {name}).")
    return op


segment_sum = _segment_factory("segment_sum", jax.ops.segment_sum, None)
segment_mean = _segment_factory(
    "segment_mean",
    lambda d, i, num_segments: jax.ops.segment_sum(d, i, num_segments)
    / jnp.maximum(jax.ops.segment_sum(
        jnp.ones(d.shape[:1] + (1,) * (d.ndim - 1), d.dtype), i,
        num_segments), 1.0),
    0.0)
segment_min = _segment_factory("segment_min", jax.ops.segment_min, 0.0)
segment_max = _segment_factory("segment_max", jax.ops.segment_max, 0.0)


# ---------------------------------------------------------------------------
# message passing (geometric/message_passing/send_recv.py)
# ---------------------------------------------------------------------------

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # handled via sum/count
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _finalize(msg, dst, n, reduce_op, dtype):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments=n)
        c = jax.ops.segment_sum(jnp.ones((msg.shape[0],) + (1,) *
                                         (msg.ndim - 1), msg.dtype),
                                dst, num_segments=n)
        return s / jnp.maximum(c, 1.0)
    out = _REDUCERS[reduce_op](msg, dst, num_segments=n)
    if reduce_op in ("min", "max"):
        c = jax.ops.segment_sum(jnp.ones_like(dst, jnp.int32), dst,
                                num_segments=n)
        out = jnp.where(c.reshape((n,) + (1,) * (out.ndim - 1)) > 0, out,
                        jnp.zeros((), dtype))
    return out


def _send_u_recv_impl(x, src, dst, *, reduce_op, n):
    return _finalize(x[src], dst, n, reduce_op, x.dtype)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x rows at src_index, segment-reduce them at dst_index.

    Reference: geometric/message_passing/send_recv.py send_u_recv."""
    x, src_index, dst_index = wrap(x), wrap(src_index), wrap(dst_index)
    n = int(out_size) if out_size is not None else x.shape[0]
    return apply("send_u_recv", _send_u_recv_impl,
                 (x, src_index, dst_index),
                 {"reduce_op": reduce_op, "n": n})


_MSG_OPS = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide,
}


def _send_ue_recv_impl(x, y, src, dst, *, message_op, reduce_op, n):
    msg = _MSG_OPS[message_op](x[src], y)
    return _finalize(msg, dst, n, reduce_op, x.dtype)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine source-node features with edge features, reduce at dst.

    Reference: geometric/message_passing/send_recv.py send_ue_recv."""
    x, y = wrap(x), wrap(y)
    src_index, dst_index = wrap(src_index), wrap(dst_index)
    n = int(out_size) if out_size is not None else x.shape[0]
    return apply("send_ue_recv", _send_ue_recv_impl,
                 (x, y, src_index, dst_index),
                 {"message_op": message_op, "reduce_op": reduce_op, "n": n})


def _send_uv_impl(x, y, src, dst, *, message_op):
    return _MSG_OPS[message_op](x[src], y[dst])


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge combination of source and destination node features.

    Reference: geometric/message_passing/send_recv.py send_uv."""
    return apply("send_uv", _send_uv_impl,
                 (wrap(x), wrap(y), wrap(src_index), wrap(dst_index)),
                 {"message_op": message_op})


# ---------------------------------------------------------------------------
# reindex / sampling (host-side: output shapes are data-dependent)
# ---------------------------------------------------------------------------

def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference:
    geometric/reindex.py reindex_graph)."""
    xs = np.asarray(wrap(x)._value)
    nbr = np.asarray(wrap(neighbors)._value)
    cnt = np.asarray(wrap(count)._value)
    # reference keeps x's ids first, in order
    order = {v: i for i, v in enumerate(xs.tolist())}
    nxt = len(order)
    for v in nbr.tolist():
        if v not in order:
            order[v] = nxt
            nxt += 1
    remap = np.vectorize(order.get)
    reindex_src = remap(nbr).astype(np.int64)
    dst = np.repeat(np.arange(len(xs)), cnt).astype(np.int64)
    out_nodes = np.array(sorted(order, key=order.get), dtype=xs.dtype)
    return (Tensor(jnp.asarray(reindex_src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are lists per edge type.

    Reference: geometric/reindex.py reindex_heter_graph."""
    xs = np.asarray(wrap(x)._value)
    order = {v: i for i, v in enumerate(xs.tolist())}
    nxt = len(order)
    srcs, dsts = [], []
    for nb, ct in zip(neighbors, count):
        nb = np.asarray(wrap(nb)._value)
        ct = np.asarray(wrap(ct)._value)
        for v in nb.tolist():
            if v not in order:
                order[v] = nxt
                nxt += 1
        remap = np.vectorize(order.get)
        srcs.append(remap(nb).astype(np.int64))
        dsts.append(np.repeat(np.arange(len(xs)), ct).astype(np.int64))
    out_nodes = np.array(sorted(order, key=order.get), dtype=xs.dtype)
    return (Tensor(jnp.asarray(np.concatenate(srcs))),
            Tensor(jnp.asarray(np.concatenate(dsts))),
            Tensor(jnp.asarray(out_nodes)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniformly sample up to sample_size neighbors per input node from a
    CSC graph (reference: geometric/sampling/neighbors.py sample_neighbors;
    CPU kernel phi/kernels/cpu/graph_sample_neighbors_kernel.cc)."""
    r = np.asarray(wrap(row)._value)
    cp = np.asarray(wrap(colptr)._value)
    nodes = np.asarray(wrap(input_nodes)._value)
    rng = np.random.RandomState(np.uint32(len(nodes) * 2654435761 % 2**31))
    out, cnt, out_eids = [], [], []
    e = np.asarray(wrap(eids)._value) if eids is not None else None
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        nbrs = r[beg:end]
        idx = np.arange(beg, end)
        if 0 <= sample_size < len(nbrs):
            pick = rng.choice(len(nbrs), sample_size, replace=False)
            nbrs = nbrs[pick]
            idx = idx[pick]
        out.append(nbrs)
        cnt.append(len(nbrs))
        if return_eids and e is not None:
            out_eids.append(e[idx])
    res = (Tensor(jnp.asarray(np.concatenate(out) if out else
                              np.empty(0, r.dtype))),
           Tensor(jnp.asarray(np.array(cnt, np.int32))))
    if return_eids and e is not None:
        res = res + (Tensor(jnp.asarray(np.concatenate(out_eids))),)
    return res


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted (without-replacement) neighbor sampling.

    Reference: geometric/sampling/neighbors.py weighted_sample_neighbors."""
    r = np.asarray(wrap(row)._value)
    cp = np.asarray(wrap(colptr)._value)
    w = np.asarray(wrap(edge_weight)._value).astype(np.float64)
    nodes = np.asarray(wrap(input_nodes)._value)
    rng = np.random.RandomState(np.uint32(len(nodes) * 40503 % 2**31))
    out, cnt, out_eids = [], [], []
    e = np.asarray(wrap(eids)._value) if eids is not None else None
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        nbrs = r[beg:end]
        idx = np.arange(beg, end)
        if 0 <= sample_size < len(nbrs):
            pw = w[beg:end]
            pw = pw / pw.sum() if pw.sum() > 0 else None
            pick = rng.choice(len(nbrs), sample_size, replace=False, p=pw)
            nbrs = nbrs[pick]
            idx = idx[pick]
        out.append(nbrs)
        cnt.append(len(nbrs))
        if return_eids and e is not None:
            out_eids.append(e[idx])
    res = (Tensor(jnp.asarray(np.concatenate(out) if out else
                              np.empty(0, r.dtype))),
           Tensor(jnp.asarray(np.array(cnt, np.int32))))
    if return_eids and e is not None:
        res = res + (Tensor(jnp.asarray(np.concatenate(out_eids))),)
    return res


# ---------------------------------------------------------------------------
# in-memory CSR/CSC graph store (host-side)
# ---------------------------------------------------------------------------

class Graph:
    """Host-side in-memory graph store in CSC layout, feeding the sampling
    ops above.

    Reference analog: the PS graph table
    (paddle/fluid/distributed/ps/table/common_graph_table.h) scoped to
    single-host — it stores adjacency per node with uniform/weighted
    neighbor sampling and feature lookup; multi-server sharding is the PS
    fleet's job, not the store's. TPU design: graph topology and sampling
    stay on host numpy (data-dependent shapes don't jit); only the sampled
    minibatch (features + reindexed edges) crosses to the device.

    Construct from an edge_index `[2, E]` (src, dst rows). Internally keeps
    CSC (per-DST inbound neighbor lists: `colptr`/`row`), matching what
    `sample_neighbors(row, colptr, nodes)` consumes.
    """

    def __init__(self, edge_index, num_nodes=None, edge_weight=None,
                 node_feat=None):
        e = np.asarray(edge_index if not isinstance(edge_index, Tensor)
                       else edge_index._value)
        if e.ndim != 2 or e.shape[0] != 2:
            raise ValueError(f"edge_index must be [2, E], got {e.shape}")
        src, dst = e[0].astype(np.int64), e[1].astype(np.int64)
        n = int(num_nodes) if num_nodes is not None else (
            int(max(src.max(), dst.max())) + 1 if src.size else 0)
        self.num_nodes = n
        self.num_edges = int(src.size)
        # sort edges by dst -> CSC; keep eids so edge features track
        order = np.argsort(dst, kind="stable")
        self._row = src[order]                      # inbound neighbor ids
        self._eids = order.astype(np.int64)         # original edge ids
        self._colptr = np.zeros(n + 1, np.int64)
        np.add.at(self._colptr, dst + 1, 1)
        np.cumsum(self._colptr, out=self._colptr)
        self._weight = (None if edge_weight is None else
                        np.asarray(edge_weight if not isinstance(
                            edge_weight, Tensor) else edge_weight._value)
                        [order].astype(np.float32))
        self.node_feat = node_feat or {}

    # -- store surface (common_graph_table analog) -------------------------
    @property
    def row(self):
        return Tensor(jnp.asarray(self._row))

    @property
    def colptr(self):
        return Tensor(jnp.asarray(self._colptr))

    def out_degree(self):
        deg = np.zeros(self.num_nodes, np.int64)
        np.add.at(deg, self._row, 1)
        return Tensor(jnp.asarray(deg))

    def in_degree(self):
        return Tensor(jnp.asarray(np.diff(self._colptr)))

    def neighbors(self, node):
        b, e = int(self._colptr[int(node)]), int(self._colptr[int(node) + 1])
        return Tensor(jnp.asarray(self._row[b:e]))

    def sample_neighbors(self, input_nodes, sample_size=-1,
                         return_eids=False, weighted=False):
        """Uniform (or weighted) without-replacement sampling of up to
        `sample_size` inbound neighbors per input node."""
        eids = Tensor(jnp.asarray(self._eids)) if return_eids else None
        if weighted:
            if self._weight is None:
                raise ValueError("graph built without edge_weight")
            return weighted_sample_neighbors(
                self.row, self.colptr, Tensor(jnp.asarray(self._weight)),
                input_nodes, sample_size=sample_size, eids=eids,
                return_eids=return_eids)
        return sample_neighbors(self.row, self.colptr, input_nodes,
                                sample_size=sample_size, eids=eids,
                                return_eids=return_eids)

    def sample_subgraph(self, input_nodes, sample_sizes):
        """Multi-hop GraphSAGE-style frontier expansion: for each hop,
        sample neighbors of the current frontier and reindex to compact
        local ids (reference: the sampling pipeline pgl/GraphSAGE builds
        from sample_neighbors + reindex_graph).

        Returns (node_ids, [(src, dst, frontier_size) per hop]) where hops
        are ordered OUTERMOST FIRST, ready to be consumed innermost-first
        by a stacked conv; node_ids[i] is the global id of local node i and
        node_ids[:frontier_size] are the hop's target nodes.
        """
        nodes = np.asarray(input_nodes if not isinstance(input_nodes, Tensor)
                           else input_nodes._value).astype(np.int64)
        hops = []
        for size in sample_sizes:
            nb, cnt = self.sample_neighbors(Tensor(jnp.asarray(nodes)),
                                            sample_size=size)
            src, dst, out_nodes = reindex_graph(
                Tensor(jnp.asarray(nodes)), nb, cnt)
            hops.append((src, dst, len(nodes)))
            nodes = np.asarray(out_nodes._value)
        return Tensor(jnp.asarray(nodes)), hops
