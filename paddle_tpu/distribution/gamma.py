"""Gamma-family: Gamma, Beta, Dirichlet, Exponential, Chi2 (reference:
distribution/gamma.py, beta.py, dirichlet.py, exponential.py, chi2.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _broadcast_all, _value


class Gamma(Distribution):
    """Shape/rate parameterization (reference gamma.py: concentration,
    rate)."""

    def __init__(self, concentration, rate):
        self.concentration, self.rate = _broadcast_all(concentration, rate)
        super().__init__(batch_shape=self.concentration.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.concentration.shape
        # jax.random.gamma is reparameterized (implicit diff)
        return jax.random.gamma(
            key, jnp.broadcast_to(self.concentration, shp)) / self.rate

    def _log_prob(self, value):
        a, b = self.concentration, self.rate
        return (a * jnp.log(b) + (a - 1) * jnp.log(value) - b * value
                - jax.scipy.special.gammaln(a))

    def _entropy(self):
        a, b = self.concentration, self.rate
        return (a - jnp.log(b) + jax.scipy.special.gammaln(a)
                + (1 - a) * jax.scipy.special.digamma(a))

    def _mean(self):
        return self.concentration / self.rate

    def _variance(self):
        return self.concentration / self.rate ** 2


class Exponential(Gamma):
    def __init__(self, rate):
        (rate,) = _broadcast_all(rate)
        super().__init__(jnp.ones_like(rate), rate)


class Chi2(Gamma):
    def __init__(self, df):
        (df,) = _broadcast_all(df)
        super().__init__(df / 2, jnp.full_like(df, 0.5))
        self.df = df


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha, self.beta = _broadcast_all(alpha, beta)
        super().__init__(batch_shape=self.alpha.shape)

    def _rsample(self, key, shape):
        k1, k2 = jax.random.split(key)
        shp = tuple(shape) + self.alpha.shape
        ga = jax.random.gamma(k1, jnp.broadcast_to(self.alpha, shp))
        gb = jax.random.gamma(k2, jnp.broadcast_to(self.beta, shp))
        return ga / (ga + gb)

    def _log_prob(self, value):
        a, b = self.alpha, self.beta
        return ((a - 1) * jnp.log(value) + (b - 1) * jnp.log1p(-value)
                - jax.scipy.special.betaln(a, b))

    def _entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        return (jax.scipy.special.betaln(a, b)
                - (a - 1) * dg(a) - (b - 1) * dg(b)
                + (a + b - 2) * dg(a + b))

    def _mean(self):
        return self.alpha / (self.alpha + self.beta)

    def _variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s ** 2 * (s + 1))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _value(concentration)
        super().__init__(batch_shape=self.concentration.shape[:-1],
                         event_shape=self.concentration.shape[-1:])

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.concentration.shape
        g = jax.random.gamma(key, jnp.broadcast_to(self.concentration, shp))
        return g / g.sum(-1, keepdims=True)

    def _log_prob(self, value):
        a = self.concentration
        lognorm = (jax.scipy.special.gammaln(a).sum(-1)
                   - jax.scipy.special.gammaln(a.sum(-1)))
        return ((a - 1) * jnp.log(value)).sum(-1) - lognorm

    def _entropy(self):
        a = self.concentration
        a0 = a.sum(-1)
        k = a.shape[-1]
        dg = jax.scipy.special.digamma
        lognorm = (jax.scipy.special.gammaln(a).sum(-1)
                   - jax.scipy.special.gammaln(a0))
        return (lognorm + (a0 - k) * dg(a0) - ((a - 1) * dg(a)).sum(-1))

    def _mean(self):
        return self.concentration / self.concentration.sum(-1, keepdims=True)

    def _variance(self):
        a = self.concentration
        a0 = a.sum(-1, keepdims=True)
        m = a / a0
        return m * (1 - m) / (a0 + 1)
