"""Location-scale family: Laplace, Gumbel, Cauchy, StudentT (reference:
distribution/laplace.py, gumbel.py, cauchy.py, student_t.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _broadcast_all

_EULER = 0.5772156649015329


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = _broadcast_all(loc, scale)
        super().__init__(batch_shape=self.loc.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.loc.shape
        u = jax.random.uniform(key, shp, self.loc.dtype, minval=-0.5 + 1e-7,
                               maxval=0.5)
        return self.loc - self.scale * jnp.sign(u) * jnp.log1p(
            -2 * jnp.abs(u))

    def _log_prob(self, value):
        return -jnp.abs(value - self.loc) / self.scale - \
            jnp.log(2 * self.scale)

    def _entropy(self):
        return 1 + jnp.log(2 * self.scale)

    def _mean(self):
        return self.loc

    def _variance(self):
        return 2 * self.scale ** 2


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = _broadcast_all(loc, scale)
        super().__init__(batch_shape=self.loc.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.loc.shape
        return self.loc + self.scale * jax.random.gumbel(key, shp,
                                                         self.loc.dtype)

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -(z + jnp.exp(-z)) - jnp.log(self.scale)

    def _entropy(self):
        return jnp.log(self.scale) + 1 + _EULER

    def _mean(self):
        return self.loc + self.scale * _EULER

    def _variance(self):
        return (math.pi ** 2 / 6) * self.scale ** 2


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = _broadcast_all(loc, scale)
        super().__init__(batch_shape=self.loc.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.loc.shape
        return self.loc + self.scale * jax.random.cauchy(key, shp,
                                                         self.loc.dtype)

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        return -jnp.log(math.pi * self.scale * (1 + z ** 2))

    def _entropy(self):
        return jnp.log(4 * math.pi * self.scale)

    def _mean(self):
        return jnp.full_like(self.loc, jnp.nan)  # undefined

    def _variance(self):
        return jnp.full_like(self.loc, jnp.nan)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df, self.loc, self.scale = _broadcast_all(df, loc, scale)
        super().__init__(batch_shape=self.loc.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.loc.shape
        t = jax.random.t(key, jnp.broadcast_to(self.df, shp), shp,
                         self.loc.dtype)
        return self.loc + self.scale * t

    def _log_prob(self, value):
        z = (value - self.loc) / self.scale
        df = self.df
        lg = jax.scipy.special.gammaln
        return (lg((df + 1) / 2) - lg(df / 2)
                - 0.5 * jnp.log(df * math.pi) - jnp.log(self.scale)
                - (df + 1) / 2 * jnp.log1p(z ** 2 / df))

    def _entropy(self):
        df = self.df
        dg = jax.scipy.special.digamma
        lg = jax.scipy.special.gammaln
        return ((df + 1) / 2 * (dg((df + 1) / 2) - dg(df / 2))
                + 0.5 * jnp.log(df) + jax.scipy.special.betaln(
                    df / 2, jnp.full_like(df, 0.5)) + jnp.log(self.scale))

    def _mean(self):
        return jnp.where(self.df > 1, self.loc, jnp.nan)

    def _variance(self):
        v = self.scale ** 2 * self.df / (self.df - 2)
        return jnp.where(self.df > 2, v,
                         jnp.where(self.df > 1, jnp.inf, jnp.nan))
