"""Transforms and TransformedDistribution (reference:
distribution/transform.py — Transform with forward/inverse/
forward_log_det_jacobian, Affine/Exp/Sigmoid/Tanh/Power/Abs/Softmax/
StickBreaking/Chain, and transformed_distribution.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _value, _wrap


class Transform:
    """Bijector base. Subclasses define _forward, _inverse,
    _forward_log_det_jacobian (per-element; event_dims summed by the
    TransformedDistribution)."""

    event_dims = 0  # how many trailing dims one transform event consumes

    def forward(self, x):
        return _wrap(self._forward(_value(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_value(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_value(x)))

    def inverse_log_det_jacobian(self, y):
        return _wrap(-self._forward_log_det_jacobian(
            self._inverse(_value(y))))

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _value(loc)
        self.scale = _value(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _value(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh^2 x) = 2 (log 2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # principal branch

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class SoftmaxTransform(Transform):
    event_dims = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError("softmax is not a bijection")


class StickBreakingTransform(Transform):
    """R^{K-1} -> open simplex Δ^K (reference transform.py
    StickBreakingTransform)."""

    event_dims = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        cum = jnp.cumprod(1 - z, -1)
        cumpad = jnp.concatenate([jnp.ones_like(z[..., :1]), cum], -1)
        return zpad * cumpad

    def _inverse(self, y):
        k = y.shape[-1] - 1
        cum = 1 - jnp.cumsum(y[..., :-1], -1)
        shifted = jnp.concatenate([jnp.ones_like(y[..., :1]),
                                   cum[..., :-1]], -1)
        z = y[..., :-1] / shifted
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def _forward_log_det_jacobian(self, x):
        # dy_i/dz_i = prod_{j<i}(1-z_j) (triangular jacobian) and
        # dz_i/dx_i = sigmoid'(x_i - offset) = exp(-sp(-xo) - sp(xo))
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        cum = jnp.cumprod(1 - z, -1)
        cumpad = jnp.concatenate([jnp.ones_like(z[..., :1]),
                                  cum[..., :-1]], -1)
        return (-jax.nn.softplus(-xo) - jax.nn.softplus(xo)
                + jnp.log(cumpad)).sum(-1)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self.event_dims = max((t.event_dims for t in self.transforms),
                              default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._forward_log_det_jacobian(x)
            x = t._forward(x)
        return total


class TransformedDistribution(Distribution):
    """base distribution pushed through transforms (reference:
    transformed_distribution.py): log_prob(y) = base.log_prob(f^-1(y)) -
    log|det J_f|(f^-1(y))."""

    def __init__(self, base: Distribution, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = ChainTransform(transforms) \
            if len(transforms) != 1 else transforms[0]
        super().__init__(batch_shape=base.batch_shape,
                         event_shape=base.event_shape)

    def _rsample(self, key, shape):
        return self.transform._forward(self.base._rsample(key, shape))

    def _sample(self, key, shape):
        return self.transform._forward(self.base._sample(key, shape))

    def _log_prob(self, value):
        # walk transforms last-to-first, reducing each jacobian over the
        # base's event dims it does NOT already cover: scalar transforms
        # (event_dims=0) over an event-shaped base must sum their
        # per-element ldj; event transforms (e.g. stick-breaking) return
        # event-reduced ldj already
        transforms = self.transform.transforms \
            if isinstance(self.transform, ChainTransform) \
            else [self.transform]
        event_ndim = len(self.base.event_shape)
        x = value
        total_ldj = 0.0
        for t in reversed(transforms):
            x = t._inverse(x)
            ldj = t._forward_log_det_jacobian(x)
            reduce_d = event_ndim - t.event_dims
            if reduce_d > 0 and getattr(ldj, "ndim", 0) >= reduce_d:
                ldj = ldj.sum(tuple(range(-reduce_d, 0)))
            total_ldj = total_ldj + ldj
        return self.base._log_prob(x) - total_ldj
