"""KL divergence registry (reference: distribution/kl.py —
register_kl dispatch table + closed forms; kl_divergence falls back to the
pair's most specific registered rule)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _wrap
from .normal import Normal
from .uniform import Uniform
from .bernoulli import Bernoulli, Geometric
from .categorical import Categorical
from .gamma import Gamma, Beta, Dirichlet
from .location_scale import Laplace
from .independent import Independent

_KL_REGISTRY: dict = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def _dispatch(p, q):
    matches = []
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            matches.append((pc, qc, fn))
    if not matches:
        raise NotImplementedError(
            f"no KL(p || q) rule for ({type(p).__name__}, "
            f"{type(q).__name__})")
    # most specific match (reference: total order by subclass depth)
    matches.sort(key=lambda m: (len(m[0].__mro__) + len(m[1].__mro__)),
                 reverse=True)
    return matches[0][2]


def kl_divergence(p: Distribution, q: Distribution):
    return _wrap(_dispatch(p, q)(p, q))


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    # infinite when p's support is not inside q's
    result = jnp.log((q.high - q.low) / (p.high - p.low))
    return jnp.where((q.low <= p.low) & (p.high <= q.high), result, jnp.inf)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    eps = 1e-7
    pp = jnp.clip(p.probs, eps, 1 - eps)
    qp = jnp.clip(q.probs, eps, 1 - eps)
    return pp * (jnp.log(pp) - jnp.log(qp)) + \
        (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return (p.probs * (p.logits - q.logits)).sum(-1)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    dg = jax.scipy.special.digamma
    lg = jax.scipy.special.gammaln
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return ((a1 - a2) * dg(a1) - lg(a1) + lg(a2)
            + a2 * (jnp.log(b1) - jnp.log(b2)) + a1 * (b2 - b1) / b1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    dg = jax.scipy.special.digamma
    bl = jax.scipy.special.betaln
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return (bl(a2, b2) - bl(a1, b1)
            + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
            + (a2 - a1 + b2 - b1) * dg(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    dg = jax.scipy.special.digamma
    lg = jax.scipy.special.gammaln
    a, b = p.concentration, q.concentration
    a0 = a.sum(-1, keepdims=True)
    return (lg(a0[..., 0]) - lg(a).sum(-1)
            - lg(b.sum(-1)) + lg(b).sum(-1)
            + ((a - b) * (dg(a) - dg(a0))).sum(-1))


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    # log(s2/s1) + (s1 exp(-|Δμ|/s1) + |Δμ|)/s2 - 1
    abs_diff = jnp.abs(p.loc - q.loc)
    return (jnp.log(q.scale) - jnp.log(p.scale)
            + (p.scale * jnp.exp(-abs_diff / p.scale) + abs_diff) / q.scale
            - 1)


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    eps = 1e-7
    pp = jnp.clip(p.probs, eps, 1 - eps)
    qp = jnp.clip(q.probs, eps, 1 - eps)
    return (jnp.log(pp) - jnp.log(qp)
            + (1 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qp)))


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p.rank != q.rank:
        raise NotImplementedError("mismatched reinterpreted ranks")
    inner = _dispatch(p.base, q.base)(p.base, q.base)
    if p.rank == 0:
        return inner
    return inner.sum(tuple(range(-p.rank, 0)))


from .discrete import Poisson, Binomial  # noqa: E402
from .multivariate_normal import MultivariateNormal  # noqa: E402


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    # KL = r_p log(r_p/r_q) + r_q - r_p
    return (p.rate * (jnp.log(jnp.maximum(p.rate, 1e-12))
                      - jnp.log(jnp.maximum(q.rate, 1e-12)))
            + q.rate - p.rate)


@register_kl(Binomial, Binomial)
def _kl_binomial(p, q):
    # closed form n * KL(Bern(p) || Bern(q)) requires equal trial counts
    import numpy as _np
    if not _np.array_equal(_np.asarray(p.total_count),
                           _np.asarray(q.total_count)):
        raise NotImplementedError(
            "KL(Binomial || Binomial) with different total_count has no "
            "closed form here")
    pp = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qq = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    per_trial = pp * (jnp.log(pp) - jnp.log(qq)) \
        + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qq))
    return p.total_count * per_trial


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    import jax
    d = p.loc.shape[-1]
    lq, lp = q._scale_tril, p._scale_tril
    # tr(Sigma_q^-1 Sigma_p) via triangular solves on the cholesky factors
    m = jax.scipy.linalg.solve_triangular(lq, lp, lower=True)
    tr = jnp.sum(m * m, axis=(-2, -1))
    diff = q.loc - p.loc
    z = jax.scipy.linalg.solve_triangular(lq, diff[..., None],
                                          lower=True)[..., 0]
    maha = jnp.sum(z * z, axis=-1)
    log_det = 2.0 * (jnp.sum(jnp.log(jnp.diagonal(lq, axis1=-2, axis2=-1)),
                             axis=-1)
                     - jnp.sum(jnp.log(jnp.diagonal(lp, axis1=-2, axis2=-1)),
                               axis=-1))
    return 0.5 * (tr + maha - d + log_det)
