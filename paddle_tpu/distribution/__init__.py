"""paddle_tpu.distribution — probability distributions.

Reference analog: python/paddle/distribution/ (Distribution base
distribution.py, Normal, Uniform, Bernoulli, Beta, Categorical, Dirichlet,
Multinomial, Gamma, Exponential, Laplace, LogNormal, Gumbel, Geometric,
Cauchy, StudentT, Binomial, Poisson, TransformedDistribution, transform.py,
Independent, kl.py registry).

TPU-native: samplers draw jax.random bits through the framework RNG
(ops.random.next_key, honoring paddle.seed and traced-mode keys); densities
are pure jnp math on Tensor values, so log_prob/entropy trace and
differentiate under jit/grad like every other op.
"""
from .distribution import Distribution  # noqa: F401
from .normal import Normal, LogNormal  # noqa: F401
from .uniform import Uniform  # noqa: F401
from .bernoulli import Bernoulli, Geometric  # noqa: F401
from .categorical import Categorical, Multinomial  # noqa: F401
from .gamma import Gamma, Beta, Dirichlet, Exponential, Chi2  # noqa: F401
from .location_scale import Laplace, Gumbel, Cauchy, StudentT  # noqa: F401
from .transformed import (  # noqa: F401
    Transform, AffineTransform, ExpTransform, SigmoidTransform,
    TanhTransform, PowerTransform, ChainTransform, AbsTransform,
    SoftmaxTransform, StickBreakingTransform, TransformedDistribution,
)
from .independent import Independent  # noqa: F401
from .discrete import Poisson, Binomial, ContinuousBernoulli  # noqa: F401
from .multivariate_normal import MultivariateNormal  # noqa: F401
from .exponential_family import ExponentialFamily  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401

__all__ = [
    "Distribution", "Normal", "LogNormal", "Uniform", "Bernoulli",
    "Geometric", "Categorical", "Multinomial", "Gamma", "Beta", "Dirichlet",
    "Exponential", "Chi2", "Laplace", "Gumbel", "Cauchy", "StudentT",
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
    "TanhTransform", "PowerTransform", "ChainTransform", "AbsTransform",
    "SoftmaxTransform", "StickBreakingTransform", "TransformedDistribution",
    "Independent", "kl_divergence", "register_kl",
]
