"""Uniform (reference: distribution/uniform.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _broadcast_all


class Uniform(Distribution):
    def __init__(self, low, high):
        self.low, self.high = _broadcast_all(low, high)
        super().__init__(batch_shape=self.low.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.low.shape
        u = jax.random.uniform(key, shp, self.low.dtype)
        return self.low + (self.high - self.low) * u

    def _log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def _entropy(self):
        return jnp.log(self.high - self.low)

    def _mean(self):
        return (self.low + self.high) / 2

    def _variance(self):
        return (self.high - self.low) ** 2 / 12
