"""Normal / LogNormal (reference: distribution/normal.py, lognormal.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _broadcast_all


class Normal(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = _broadcast_all(loc, scale)
        super().__init__(batch_shape=self.loc.shape)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.loc.shape
        eps = jax.random.normal(key, shp, self.loc.dtype)
        return self.loc + self.scale * eps

    def _log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def _entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def _mean(self):
        return self.loc

    def _variance(self):
        return self.scale ** 2

    def cdf(self, value):
        from .distribution import _value, _wrap

        v = _value(value)
        return _wrap(0.5 * (1 + jax.scipy.special.erf(
            (v - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, q):
        from .distribution import _value, _wrap

        v = _value(q)
        return _wrap(self.loc + self.scale * math.sqrt(2)
                     * jax.scipy.special.erfinv(2 * v - 1))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc, self.scale = _broadcast_all(loc, scale)
        self._base = Normal(loc, scale)
        super().__init__(batch_shape=self.loc.shape)

    def _rsample(self, key, shape):
        return jnp.exp(self._base._rsample(key, shape))

    def _log_prob(self, value):
        return self._base._log_prob(jnp.log(value)) - jnp.log(value)

    def _entropy(self):
        return self._base._entropy() + self.loc

    def _mean(self):
        return jnp.exp(self.loc + self.scale ** 2 / 2)

    def _variance(self):
        s2 = self.scale ** 2
        return (jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2)
