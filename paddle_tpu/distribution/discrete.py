"""Poisson / Binomial / ContinuousBernoulli (reference:
distribution/poisson.py, binomial.py, continuous_bernoulli.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _broadcast_all

_EPS = 1e-7


class Poisson(Distribution):
    """P(X=k) = exp(-rate) rate^k / k! (reference poisson.py:33)."""

    def __init__(self, rate):
        (self.rate,) = _broadcast_all(rate)
        super().__init__(batch_shape=self.rate.shape)

    def _sample(self, key, shape):
        shp = tuple(shape) + self.rate.shape
        from ..ops.random import _threefry_key
        return jax.random.poisson(_threefry_key(key), self.rate, shp).astype(self.rate.dtype)

    _rsample = _sample  # counts are not reparameterizable

    def _log_prob(self, value):
        rate = jnp.maximum(self.rate, _EPS)
        return value * jnp.log(rate) - rate - jax.lax.lgamma(value + 1.0)

    def _entropy(self):
        # series approximation used by the reference for large rate; exact
        # summation over a truncated support for small rate
        rate = self.rate
        ks = jnp.arange(0.0, 64.0)
        logp = (ks[:, None] * jnp.log(jnp.maximum(rate.reshape(-1), _EPS))
                - rate.reshape(-1) - jax.lax.lgamma(ks + 1.0)[:, None])
        small = -jnp.sum(jnp.exp(logp) * logp, axis=0).reshape(rate.shape)
        large = 0.5 * jnp.log(2 * jnp.pi * jnp.e * rate) \
            - 1.0 / (12.0 * rate) - 1.0 / (24.0 * rate ** 2)
        return jnp.where(rate < 16.0, small, large)

    def _mean(self):
        return self.rate

    def _variance(self):
        return self.rate


class Binomial(Distribution):
    """P(X=k) = C(n,k) p^k (1-p)^(n-k) (reference binomial.py:36)."""

    def __init__(self, total_count, probs):
        self.total_count, self.probs = _broadcast_all(total_count, probs)
        super().__init__(batch_shape=self.probs.shape)

    def _sample(self, key, shape):
        shp = tuple(shape) + self.probs.shape
        return jax.random.binomial(
            key, self.total_count, self.probs, shape=shp).astype(
                self.probs.dtype)

    _rsample = _sample

    def _log_prob(self, value):
        n, p = self.total_count, jnp.clip(self.probs, _EPS, 1 - _EPS)
        log_comb = (jax.lax.lgamma(n + 1.0) - jax.lax.lgamma(value + 1.0)
                    - jax.lax.lgamma(n - value + 1.0))
        return log_comb + value * jnp.log(p) + (n - value) * jnp.log1p(-p)

    def _entropy(self):
        # exact truncated-support sum (reference computes the same sum).
        # The sum length is data-dependent, so total_count must be concrete:
        # entropy() is eager-only (calling it under jit/to_static tracing
        # gets a clear error instead of a ConcretizationTypeError).
        n, p = self.total_count, self.probs
        nmax = jax.core.concrete_or_error(
            None, jnp.max(n),
            "Binomial.entropy() needs a concrete total_count — its "
            "truncated-support sum length is data-dependent. Call it "
            "outside jit/to_static, or hoist entropy() out of the traced "
            "region.")
        kmax = int(nmax) + 1
        ks = jnp.arange(0.0, kmax)
        nf, pf = n.reshape(-1), jnp.clip(p.reshape(-1), _EPS, 1 - _EPS)
        log_comb = (jax.lax.lgamma(nf + 1.0)[None]
                    - jax.lax.lgamma(ks + 1.0)[:, None]
                    - jax.lax.lgamma(nf - ks[:, None] + 1.0))
        logp = log_comb + ks[:, None] * jnp.log(pf) \
            + (nf - ks[:, None]) * jnp.log1p(-pf)
        valid = ks[:, None] <= nf
        ent = -jnp.sum(jnp.where(valid, jnp.exp(logp) * logp, 0.0), axis=0)
        return ent.reshape(n.shape)

    def _mean(self):
        return self.total_count * self.probs

    def _variance(self):
        return self.total_count * self.probs * (1 - self.probs)


class ContinuousBernoulli(Distribution):
    """Continuous relaxation on [0,1] (reference
    continuous_bernoulli.py:47; Loaiza-Ganem & Cunningham 2019)."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        (self.probs,) = _broadcast_all(probs)
        self._lims = lims
        super().__init__(batch_shape=self.probs.shape)

    def _log_norm_const(self):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        cut = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(cut, 0.25, p)
        log_c = jnp.log(
            (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
            / jnp.maximum(1.0 - 2.0 * safe, _EPS))
        taylor = jnp.log(2.0) + 4.0 / 3.0 * (p - 0.5) ** 2 \
            + 104.0 / 45.0 * (p - 0.5) ** 4
        return jnp.where(cut, taylor, log_c)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.probs.shape
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        u = jax.random.uniform(key, shp, p.dtype, minval=_EPS,
                               maxval=1 - _EPS)
        cut = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(cut, 0.25, p)
        icdf = (jnp.log1p(u * (2.0 * safe - 1.0) / (1.0 - safe))
                / (jnp.log(safe) - jnp.log1p(-safe)))
        return jnp.where(cut, u, icdf)

    def _log_prob(self, value):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        return (value * jnp.log(p) + (1 - value) * jnp.log1p(-p)
                + self._log_norm_const())

    def _mean(self):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        cut = (p > self._lims[0]) & (p < self._lims[1])
        safe = jnp.where(cut, 0.25, p)
        m = safe / (2.0 * safe - 1.0) \
            + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * safe))
        taylor = 0.5 + (p - 0.5) / 3.0 + 16.0 / 45.0 * (p - 0.5) ** 3
        return jnp.where(cut, taylor, m)

    def _variance(self):
        # numerically-stable second moment via quadrature on [0, 1]
        xs = jnp.linspace(0.0, 1.0, 257)
        p = jnp.clip(self.probs, _EPS, 1 - _EPS).reshape(-1)
        lp = (xs[:, None] * jnp.log(p) + (1 - xs[:, None]) * jnp.log1p(-p)
              + self._log_norm_const().reshape(-1)[None])
        w = jnp.exp(lp) / jnp.sum(jnp.exp(lp), axis=0)
        m1 = jnp.sum(xs[:, None] * w, axis=0)
        m2 = jnp.sum(xs[:, None] ** 2 * w, axis=0)
        return (m2 - m1 ** 2).reshape(self.probs.shape)

    def _entropy(self):
        # E[-log p(x)] by quadrature over the unit support
        xs = jnp.linspace(0.0, 1.0, 257)
        p = jnp.clip(self.probs, _EPS, 1 - _EPS).reshape(-1)
        lp = (xs[:, None] * jnp.log(p) + (1 - xs[:, None]) * jnp.log1p(-p)
              + self._log_norm_const().reshape(-1)[None])
        w = jnp.exp(lp) / jnp.sum(jnp.exp(lp), axis=0)
        return (-jnp.sum(w * lp, axis=0)).reshape(self.probs.shape)
