"""Categorical / Multinomial (reference: distribution/categorical.py,
multinomial.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _value

_EPS = 1e-9


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if logits is not None:
            self.logits = _value(logits)
            self.logits = self.logits - jax.scipy.special.logsumexp(
                self.logits, axis=-1, keepdims=True)
        else:
            p = _value(probs)
            p = p / p.sum(-1, keepdims=True)
            self.logits = jnp.log(p + _EPS)
        self.probs = jnp.exp(self.logits)
        super().__init__(batch_shape=self.logits.shape[:-1])

    @property
    def n_categories(self):
        return self.logits.shape[-1]

    def _sample(self, key, shape):
        shp = tuple(shape) + self.batch_shape
        return jax.random.categorical(key, self.logits, shape=shp).astype(
            jnp.int32)

    _rsample = _sample

    def _log_prob(self, value):
        idx = value.astype(jnp.int32)
        return jnp.take_along_axis(
            jnp.broadcast_to(self.logits, idx.shape + (self.n_categories,)),
            idx[..., None], axis=-1)[..., 0]

    def _entropy(self):
        return -(self.probs * self.logits).sum(-1)

    def _mean(self):
        return (self.probs *
                jnp.arange(self.n_categories, dtype=self.probs.dtype)).sum(-1)

    def _variance(self):
        k = jnp.arange(self.n_categories, dtype=self.probs.dtype)
        m = self._mean()
        return (self.probs * k ** 2).sum(-1) - m ** 2


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _value(probs)
        self.probs = p / p.sum(-1, keepdims=True)
        self.logits = jnp.log(self.probs + _EPS)
        super().__init__(batch_shape=self.probs.shape[:-1],
                         event_shape=self.probs.shape[-1:])

    def _sample(self, key, shape):
        shp = tuple(shape) + self.batch_shape
        draws = jax.random.categorical(
            key, self.logits, axis=-1,
            shape=(self.total_count,) + shp)
        onehot = jax.nn.one_hot(draws, self.probs.shape[-1],
                                dtype=self.probs.dtype)
        return onehot.sum(0)

    _rsample = _sample

    def _log_prob(self, value):
        lgamma = jax.scipy.special.gammaln
        n = jnp.asarray(self.total_count, self.probs.dtype)
        return (lgamma(n + 1) - lgamma(value + 1).sum(-1)
                + (value * self.logits).sum(-1))

    def _mean(self):
        return self.total_count * self.probs

    def _variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def _entropy(self):
        # no closed form; Monte-Carlo-free bound not in reference either —
        # use the sum of categorical entropies scaled (reference raises too)
        raise NotImplementedError("Multinomial entropy has no closed form")
