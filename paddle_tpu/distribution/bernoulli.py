"""Bernoulli / Geometric (reference: distribution/bernoulli.py,
geometric.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _broadcast_all

_EPS = 1e-7


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            (self.probs,) = _broadcast_all(probs)
            self.logits = jnp.log(self.probs + _EPS) - \
                jnp.log1p(-self.probs + _EPS)
        else:
            (self.logits,) = _broadcast_all(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(batch_shape=self.probs.shape)

    def _sample(self, key, shape):
        shp = tuple(shape) + self.probs.shape
        return jax.random.bernoulli(key, self.probs, shp).astype(
            self.probs.dtype)

    _rsample = _sample  # no reparameterization; kept for API parity

    def _log_prob(self, value):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        return value * jnp.log(p) + (1 - value) * jnp.log1p(-p)

    def _entropy(self):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

    def _mean(self):
        return self.probs

    def _variance(self):
        return self.probs * (1 - self.probs)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p for k = 0, 1, ... (reference geometric.py)."""

    def __init__(self, probs):
        (self.probs,) = _broadcast_all(probs)
        super().__init__(batch_shape=self.probs.shape)

    def _sample(self, key, shape):
        shp = tuple(shape) + self.probs.shape
        u = jax.random.uniform(key, shp, self.probs.dtype, minval=_EPS)
        return jnp.floor(jnp.log(u) / jnp.log1p(-self.probs))

    _rsample = _sample

    def _log_prob(self, value):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        return value * jnp.log1p(-p) + jnp.log(p)

    def _entropy(self):
        p = jnp.clip(self.probs, _EPS, 1 - _EPS)
        return -((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p

    def _mean(self):
        return (1 - self.probs) / self.probs

    def _variance(self):
        return (1 - self.probs) / self.probs ** 2
