"""Distribution base (reference: distribution/distribution.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops import random as rnd


def _value(x):
    if isinstance(x, Tensor):
        return x._value
    a = np.asarray(x)
    if a.dtype.kind in "iub":  # parameters given as python ints
        a = a.astype(np.float32)
    return jnp.asarray(a)


def _wrap(v):
    return Tensor(v)


def _broadcast_all(*vals):
    arrs = [_value(v) for v in vals]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [jnp.broadcast_to(a, shape) for a in arrs]


class Distribution:
    """Base API: sample/rsample, log_prob/prob, entropy, mean/variance,
    kl_divergence (reference distribution.py:40)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    # subclasses implement _sample(key, shape) / _rsample(key, shape)
    def sample(self, shape=()):
        key = rnd.next_key()
        return _wrap(self._sample(key, tuple(shape)))

    def rsample(self, shape=()):
        key = rnd.next_key()
        return _wrap(self._rsample(key, tuple(shape)))

    def _sample(self, key, shape):
        return self._rsample(key, shape)

    def _rsample(self, key, shape):
        raise NotImplementedError

    def log_prob(self, value):
        return _wrap(self._log_prob(_value(value)))

    def prob(self, value):
        return _wrap(jnp.exp(self._log_prob(_value(value))))

    def _log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        return _wrap(self._entropy())

    def _entropy(self):
        raise NotImplementedError

    @property
    def mean(self):
        return _wrap(self._mean())

    @property
    def variance(self):
        return _wrap(self._variance())

    def _mean(self):
        raise NotImplementedError

    def _variance(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape
