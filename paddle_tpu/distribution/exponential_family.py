"""ExponentialFamily base (reference: distribution/exponential_family.py).

The reference derives entropy via the Bregman divergence of the
log-normalizer (autograd on `_log_normalizer`); here the same derivation
uses jax.grad — subclasses supply natural parameters and the
log-normalizer, entropy comes for free."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _wrap


class ExponentialFamily(Distribution):
    """Subclasses define `_natural_parameters` (tuple of arrays),
    `_log_normalizer(*nat)`, and `_mean_carrier_measure`.

    H = -E[carrier] + A(eta) - sum_i eta_i * dA/deta_i
    (reference exponential_family.py:39)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nat = [jnp.asarray(p) for p in self._natural_parameters]

        def log_norm_sum(*ps):
            return jnp.sum(self._log_normalizer(*ps))

        grads = jax.grad(log_norm_sum, argnums=tuple(range(len(nat))))(*nat)
        result = -jnp.asarray(self._mean_carrier_measure) \
            + self._log_normalizer(*nat)
        for p, g in zip(nat, grads):
            term = p * g
            # reduce any event dims beyond the batch shape
            extra = term.ndim - len(self.batch_shape)
            if extra > 0:
                term = jnp.sum(term, axis=tuple(range(-extra, 0)))
            result = result - term
        return _wrap(result)
