"""Independent (reference: distribution/independent.py) — reinterprets
trailing batch dims of a base distribution as event dims."""
from __future__ import annotations

from .distribution import Distribution


class Independent(Distribution):
    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        if self.rank > len(base.batch_shape):
            raise ValueError(
                f"cannot reinterpret {self.rank} dims of batch shape "
                f"{base.batch_shape}")
        cut = len(base.batch_shape) - self.rank
        super().__init__(
            batch_shape=base.batch_shape[:cut],
            event_shape=base.batch_shape[cut:] + base.event_shape)

    def _rsample(self, key, shape):
        return self.base._rsample(key, shape)

    def _sample(self, key, shape):
        return self.base._sample(key, shape)

    def _log_prob(self, value):
        lp = self.base._log_prob(value)
        if self.rank == 0:
            return lp
        return lp.sum(tuple(range(-self.rank, 0)))

    def _entropy(self):
        ent = self.base._entropy()
        if self.rank == 0:
            return ent
        return ent.sum(tuple(range(-self.rank, 0)))

    def _mean(self):
        return self.base._mean()

    def _variance(self):
        return self.base._variance()
