"""MultivariateNormal (reference: distribution/multivariate_normal.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _value


class MultivariateNormal(Distribution):
    """N(loc, Sigma) parameterized by any one of covariance_matrix /
    precision_matrix / scale_tril (reference multivariate_normal.py:41)."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError(
                "pass exactly one of covariance_matrix/precision_matrix/"
                "scale_tril")
        self.loc = _value(loc)
        if scale_tril is not None:
            self._scale_tril = _value(scale_tril)
        elif covariance_matrix is not None:
            self._scale_tril = jnp.linalg.cholesky(_value(covariance_matrix))
        else:
            prec = _value(precision_matrix)
            # chol(Sigma) from chol(P): Sigma = P^-1
            chol_p = jnp.linalg.cholesky(prec)
            eye = jnp.eye(prec.shape[-1], dtype=prec.dtype)
            inv_chol = jax.scipy.linalg.solve_triangular(chol_p, eye,
                                                         lower=True)
            self._scale_tril = jnp.linalg.cholesky(
                jnp.swapaxes(inv_chol, -1, -2) @ inv_chol)
        d = self.loc.shape[-1]
        super().__init__(batch_shape=self.loc.shape[:-1], event_shape=(d,))

    @property
    def covariance_matrix(self):
        from .distribution import _wrap
        return _wrap(self._scale_tril
                     @ jnp.swapaxes(self._scale_tril, -1, -2))

    @property
    def scale_tril(self):
        from .distribution import _wrap
        return _wrap(self._scale_tril)

    def _rsample(self, key, shape):
        shp = tuple(shape) + self.loc.shape
        eps = jax.random.normal(key, shp, self.loc.dtype)
        return self.loc + jnp.einsum("...ij,...j->...i", self._scale_tril,
                                     eps)

    def _log_prob(self, value):
        d = self.loc.shape[-1]
        diff = value - self.loc
        # solve L z = diff  =>  z = L^-1 diff; |z|^2 is the Mahalanobis term
        z = jax.scipy.linalg.solve_triangular(
            self._scale_tril, diff[..., None], lower=True)[..., 0]
        half_log_det = jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)),
            axis=-1)
        return (-0.5 * jnp.sum(z * z, axis=-1) - half_log_det
                - 0.5 * d * jnp.log(2 * jnp.pi))

    def _entropy(self):
        d = self.loc.shape[-1]
        half_log_det = jnp.sum(
            jnp.log(jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)),
            axis=-1)
        return 0.5 * d * (1.0 + jnp.log(2 * jnp.pi)) + half_log_det

    def _mean(self):
        return self.loc

    def _variance(self):
        return jnp.sum(self._scale_tril ** 2, axis=-1)
