"""paddle_tpu.analysis.commcheck — the collective-schedule auditor.

The fifth analysis pillar. tracelint audits what we *wrote*, lockcheck
and tpu-san what we *ran*, graphcheck what XLA *compiled per program* —
this module audits what the pod *agrees on*: the ordered sequence of
collectives every host is about to dispatch. The single worst multi-host
failure mode — hosts silently disagreeing on which collectives they
issue, in what order, over which axes — surfaces on real metal as an
unattributable ICI/DCN hang that a watchdog can only blame as
"stalled". commcheck catches it twice:

* **Statically** — :func:`record_program` walks the jaxpr (explicit
  collectives: the ring-attention `ppermute`s inside shard_map bodies,
  `psum`/`all_gather`/... primitives, sub-jaxprs inlined in dispatch
  order) and the compiled HLO (the GSPMD-derived `all-reduce`/
  `all-gather`/`reduce-scatter`/... ops with their replica groups and
  reduce ops) of every framework entrypoint, canonicalizes the ordered
  schedule into line-number-free entries, and fingerprints it per
  ``site::program``. The checked-in ``.commcheck_baseline.json``
  (driven by ``tools/comm_audit.py``, exit 0/1/2 + ``--write-baseline``
  like graph_audit) then fails any PR that silently adds an all-gather
  or reorders a reduce-scatter until it is re-ratcheted.

* **At runtime, cross-host** — with a coordination store attached
  (:func:`attach_store`, wired by ``init_parallel_env``), every host
  publishes its schedule fingerprint plus a rolling dispatch-sequence
  hash to the ``/commcheck/<epoch>/`` keyspace before the FIRST
  dispatch of each entrypoint (epoch-namespaced like the replica
  transport, so an elastic relaunch re-verifies under a fresh
  namespace). Any disagreement — content OR order — raises a typed
  :class:`CollectiveScheduleMismatchError` naming the divergent host
  and the first divergent collective on ALL hosts instead of a hang.
  A wedge with a pending mismatch upgrades the `TrainWatchdog` blame
  from "stalled" to the divergent host+collective.

Opt-in via ``PADDLE_TPU_COMMCHECK=1`` (or :func:`enable`) with the
established zero-overhead-off discipline: every framework hook reduces
to one module-flag check when off. Schedules are keyed
``<site>::<program>`` where ``program`` is a short digest of the
entrypoint's input-aval signature — deterministic, line-number-free,
stable across code motion. Counters export as the ``commcheck``
collector on the obs registry (docs/observability.md); the rule
catalogue and workflows live in docs/static_analysis.md.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time

__all__ = [
    "RULE", "enable", "disable", "enabled", "reset",
    "record_program", "check_entrypoint", "extract_schedule",
    "jaxpr_schedule", "hlo_schedule", "program_key", "schedules",
    "errors", "report", "load_baseline", "write_baseline",
    "new_schedules", "attach_store", "detach_store", "verifier",
    "pending_mismatch", "CollectiveScheduleMismatchError",
    "OBS_COLLECTOR",
]

_ENV = "PADDLE_TPU_COMMCHECK"
_ENV_TIMEOUT = "PADDLE_TPU_COMMCHECK_TIMEOUT_S"

#: the one rule key the ratchet reports under (``<site>::commcheck``)
RULE = "commcheck"

#: obs-registry collector name (docs/observability.md)
OBS_COLLECTOR = "commcheck"

#: store keyspace root for the cross-host verifier
STORE_PREFIX = "/commcheck"

#: jaxpr primitives that ARE collectives (explicit, pre-GSPMD: what
#: shard_map bodies and manual lax collectives bind)
_JAXPR_COLLECTIVES = {
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "pbroadcast",
}

#: HLO collective kinds (the GSPMD-derived schedule)
_HLO_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

#: eqn params worth canonicalizing into a schedule entry (whitelist —
#: anything else may hold jaxprs/functions or unstable reprs)
_ENTRY_PARAMS = ("perm", "all_gather_dimension", "tiled", "split_axis",
                 "concat_axis", "scatter_dimension", "axis_index_groups")

#: recursion cap for in-order sub-jaxpr inlining
_MAX_DEPTH = 32

_off_values = ("", "0", "false", "off", "no")


def _env_on(name, default=""):
    return os.environ.get(name, default).strip().lower() not in _off_values


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


_enabled = _env_on(_ENV)


class CollectiveScheduleMismatchError(RuntimeError):
    """Hosts disagree on the collective schedule of an entrypoint.

    Raised on EVERY host of the cohort (the divergent one included) so
    the job dies typed and attributable instead of hanging in a
    collective. `host` names the blamed (divergent-from-consensus)
    host, `site` the entrypoint it diverged at, and
    `first_divergent_collective` the first schedule entry that differs
    from the consensus schedule."""

    def __init__(self, message, *, host=None, site=None,
                 first_divergent_collective=None, index=None):
        super().__init__(message)
        self.host = host
        self.site = site
        self.first_divergent_collective = first_divergent_collective
        self.index = index

    @property
    def phase(self):
        # TrainingStalledError-compatible blame surface: on_stall
        # consumers read err.host / err.phase
        return self.site


class _Registry:
    """Global recorder. Guarded by a RAW threading.Lock on purpose (the
    analysis recorders must not observe themselves through lockcheck)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._programs = {}   # key -> {site, fingerprint, collectives}
        self._errors = {}     # site -> message (extraction failures)
        self.counters = {"programs": 0, "collectives_seen": 0,
                         "verified": 0, "mismatches": 0,
                         "verify_timeouts": 0}

    def note_program(self, key, site, fingerprint, schedule):
        with self._mu:
            self._programs[key] = {"site": site, "fingerprint": fingerprint,
                                   "collectives": list(schedule)}
            self.counters["programs"] += 1
            self.counters["collectives_seen"] += len(schedule)

    def note_error(self, site, message):
        with self._mu:
            self._errors.setdefault(site, message)

    def bump(self, name, n=1):
        with self._mu:
            self.counters[name] = self.counters.get(name, 0) + n

    def schedules(self):
        with self._mu:
            return {k: dict(v) for k, v in self._programs.items()}

    def errors(self):
        with self._mu:
            return dict(self._errors)

    def reset(self):
        with self._mu:
            self._programs = {}
            self._errors = {}
            self.counters = {k: 0 for k in self.counters}

    def report(self):
        with self._mu:
            return {
                "schedules": {k: dict(v)
                              for k, v in self._programs.items()},
                "errors": dict(self._errors),
                "counters": dict(self.counters),
            }


_registry = _Registry()


def registry():
    return _registry


def _obs_collect():
    rep = _registry.report()
    out = {"enabled": int(_enabled),
           "programs_recorded": len(rep["schedules"]),
           "errors": len(rep["errors"])}
    out.update(rep["counters"])
    return out


def enable():
    """Turn the auditor on (hooks record on their next cold compile) and
    register the ``commcheck`` obs collector."""
    global _enabled
    _enabled = True
    try:
        from ..obs.metrics import registry as _obs
        _obs().register_collector(OBS_COLLECTOR, _obs_collect)
    except Exception:  # tpu-lint: disable=TL007 — obs is optional here:
        pass           # the auditor must work without the registry


def disable():
    global _enabled
    _enabled = False
    try:
        from ..obs.metrics import registry as _obs
        _obs().unregister_collector(OBS_COLLECTOR)
    except Exception:  # tpu-lint: disable=TL007 — symmetric with enable
        pass


def enabled():
    return _enabled


def reset():
    """Clear all recorded state (the enable flag and an attached
    verifier stay)."""
    _registry.reset()


if _enabled:
    enable()     # env asked: register the collector at import


# ---------------------------------------------------------------------------
# schedule extraction: jaxpr (explicit collectives) + HLO (GSPMD-derived)
# ---------------------------------------------------------------------------

def _is_literal(v):
    return type(v).__name__ == "Literal"


def _sub_jaxprs(eqn):
    """Sub-jaxprs of one eqn, in params order: pjit/scan/cond bodies
    (ClosedJaxpr) AND shard_map bodies (raw Jaxpr param values)."""
    subs = []
    for v in eqn.params.values():
        for q in (v if isinstance(v, (list, tuple)) else (v,)):
            j = getattr(q, "jaxpr", None)
            j = q if j is None else j
            if hasattr(j, "eqns") and hasattr(j, "invars"):
                subs.append(j)
    return subs


def _axes_of(eqn):
    """Canonical mesh-axis names of a collective eqn (psum binds `axes`,
    the rest `axis_name`; both may be one name or a tuple)."""
    p = eqn.params
    ax = p.get("axes", p.get("axis_name"))
    if ax is None:
        return ()
    if isinstance(ax, (tuple, list, frozenset, set)):
        return tuple(sorted(str(a) for a in ax))
    return (str(ax),)


def _operand_sig(eqn):
    """dtype+shape of the first non-literal operand ('?' when absent)."""
    for v in eqn.invars:
        if _is_literal(v):
            continue
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "shape"):
            return f"{aval.dtype}{list(aval.shape)}"
    return "?"


def _entry_extras(eqn):
    out = []
    for name in _ENTRY_PARAMS:
        if name in eqn.params:
            v = eqn.params[name]
            if isinstance(v, (list, tuple)):
                v = tuple(tuple(x) if isinstance(x, (list, tuple)) else x
                          for x in v)
            out.append(f"{name}={v}")
    return " ".join(out)


def jaxpr_schedule(jaxpr, _depth=0):
    """Ordered collective entries of a (Closed)Jaxpr: an in-place
    depth-first walk (each eqn's sub-jaxprs — scan/pjit/shard_map bodies
    — are inlined AT the eqn's position, so the sequence matches
    dispatch order), one canonical string per collective primitive."""
    j = getattr(jaxpr, "jaxpr", None)
    j = jaxpr if j is None or not hasattr(j, "eqns") else j
    out = []
    if _depth > _MAX_DEPTH:
        return out
    for e in j.eqns:
        name = e.primitive.name
        if name in _JAXPR_COLLECTIVES:
            axes = ",".join(_axes_of(e)) or "?"
            extra = _entry_extras(e)
            out.append(f"jaxpr:{name}@{axes} {_operand_sig(e)}"
                       + (f" {extra}" if extra else ""))
        for sub in _sub_jaxprs(e):
            out.extend(jaxpr_schedule(sub, _depth + 1))
    return out


_HLO_SHAPE_RE = re.compile(r"=\s+(?:\()?([a-z0-9]+)\[([0-9,]*)\]")
_TO_APPLY_RE = re.compile(r"to_apply=%?([A-Za-z_]+)")


def _scan_groups(line, attr):
    """Value of `attr=` on an HLO line, through balanced {}/[] nesting
    (covers ``{{0,1},{2,3}}`` list-of-lists AND the ``[2,2]<=[4]`` iota
    form), ending at the first top-level comma/space."""
    i = line.find(attr + "=")
    if i < 0:
        return ""
    i += len(attr) + 1
    depth = 0
    j = i
    while j < len(line):
        c = line[j]
        if c in "{[":
            depth += 1
        elif c in "}]":
            depth -= 1
        elif c in ", " and depth <= 0:
            break
        j += 1
    return line[i:j]


def hlo_schedule(hlo_text):
    """Ordered collective entries of a compiled module's HLO text: the
    GSPMD-derived schedule — kind, result dtype/shape, replica groups
    (or source-target pairs) and the reduce op (to_apply region's alpha
    prefix; numeric suffixes stripped so unrelated region renames never
    churn the fingerprint)."""
    out = []
    for line in (hlo_text or "").splitlines():
        kind = next((k for k in _HLO_COLLECTIVES if f" {k}(" in line), None)
        if kind is None:
            continue
        m = _HLO_SHAPE_RE.search(line)
        sig = f"{m.group(1)}[{m.group(2)}]" if m else "?"
        parts = [f"hlo:{kind} {sig}"]
        groups = _scan_groups(line, "replica_groups") or \
            _scan_groups(line, "source_target_pairs")
        if groups:
            parts.append(f"groups={groups}")
        ta = _TO_APPLY_RE.search(line)
        if ta:
            parts.append(f"op={ta.group(1).rstrip('_')}")
        out.append(" ".join(parts))
    return out


def extract_schedule(jaxpr, hlo_text=""):
    """The canonical ordered schedule: jaxpr-level entries (explicit
    collectives, dispatch order) followed by HLO-level entries (the
    compiled module's derived collectives, module order). Explicit
    collectives appear at both levels by design — the fingerprint only
    needs determinism, and the two views blame different bug classes
    (a shard_map body vs a GSPMD sharding change)."""
    return jaxpr_schedule(jaxpr) + hlo_schedule(hlo_text)


def fingerprint_of(schedule):
    return hashlib.sha256("\n".join(schedule).encode()).hexdigest()


def _aval_sig(args):
    import jax

    sig = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append(f"{dtype}{list(shape)}")
        else:
            sig.append(type(leaf).__name__)
    return sig


def program_key(site, args):
    """``<site>::<8-hex digest of the input-aval signature>`` — the
    baseline identity of one compiled program at one site. Line-number-
    free and deterministic across hosts/processes (aval signatures are
    pytree-ordered), so N buckets of one entrypoint ratchet
    independently while code motion never churns the key."""
    digest = hashlib.sha256(
        json.dumps(_aval_sig(args)).encode()).hexdigest()[:8]
    return f"{site}::{digest}"


# ---------------------------------------------------------------------------
# recording (the framework hooks' entry)
# ---------------------------------------------------------------------------

class Program:
    __slots__ = ("key", "site", "fingerprint", "schedule")

    def __init__(self, key, site, fingerprint, schedule):
        self.key = key
        self.site = site
        self.fingerprint = fingerprint
        self.schedule = schedule


def record_program(site, *, jit_obj=None, fn=None, args=None,
                   lowered=None, compiled=None):
    """Extract + record the collective schedule of one entrypoint.

    Two call shapes, mirroring graphcheck.audit_executable:

    * ``record_program(site, jit_obj=jitted, args=(...))`` — traces,
      lowers and compiles itself (one extra AOT compile; the engine's
      cold path, opt-in only).
    * ``record_program(site, fn=f, args=avals, lowered=l, compiled=c)``
      — the aot compile paths hand over the objects they already built.

    Returns the recorded :class:`Program`, or None on extraction
    failure — which is recorded as a (never-silently-baselined) error,
    not raised: the auditor must not break the entrypoint it audits.
    """
    try:
        import jax

        if jit_obj is not None:
            traced = jit_obj.trace(*args)
            jaxpr = traced.jaxpr
            if lowered is None:
                lowered = traced.lower()
        else:
            jaxpr = jax.jit(fn).trace(*args).jaxpr
        if compiled is None and lowered is not None:
            compiled = lowered.compile()
        hlo_text = ""
        if compiled is not None:
            try:
                hlo_text = compiled.as_text()
            except Exception:  # tpu-lint: disable=TL007 — some backends
                hlo_text = ""  # cannot render text; jaxpr entries remain
        schedule = extract_schedule(jaxpr, hlo_text)
        prog = Program(program_key(site, args), site,
                       fingerprint_of(schedule), schedule)
        _registry.note_program(prog.key, site, prog.fingerprint,
                               prog.schedule)
        return prog
    except Exception as e:  # noqa: BLE001 — never break the entrypoint
        _registry.note_error(site,
                             f"schedule extraction failed: "
                             f"{type(e).__name__}: {e}")
        return None


def check_entrypoint(site, **kw):
    """The one-line framework hook: record the entrypoint's schedule
    and, when a cross-host verifier is attached, verify it against the
    cohort before the first dispatch. Extraction failures are recorded,
    never raised; a cross-host divergence raises the typed
    :class:`CollectiveScheduleMismatchError` (that is the point)."""
    prog = record_program(site, **kw)
    v = _verifier
    if prog is not None and v is not None:
        v.verify(prog)
    return prog


# ---------------------------------------------------------------------------
# cross-host runtime verifier
# ---------------------------------------------------------------------------

def _first_divergence(canon, mine):
    """(index, entry) of the first position where `mine` departs from
    the consensus schedule `canon` (None when equal)."""
    for i in range(max(len(canon), len(mine))):
        want = canon[i] if i < len(canon) else None
        got = mine[i] if i < len(mine) else None
        if want != got:
            if got is None:
                got = f"<missing — peers run {want}>"
            return i, got
    return None, None


def _blame(recs):
    """Consensus + blame over one verify round's records ({host: rec}).

    Deterministic on every host: group by (fingerprint, rolling, site,
    program); the consensus group is the largest (ties broken toward
    the group holding the first host in sort order — the coordinator
    convention), every other host is divergent. Returns None when the
    cohort agrees, else the mismatch record to publish."""
    groups = {}
    for h, r in sorted(recs.items()):
        sig = (r["fingerprint"], r["rolling"], r["site"], r["program"])
        groups.setdefault(sig, []).append(h)
    if len(groups) <= 1:
        return None
    canon_sig = sorted(groups, key=lambda s: (-len(groups[s]),
                                              min(groups[s])))[0]
    canon_hosts = groups[canon_sig]
    blamed = sorted(h for h in recs if h not in canon_hosts)
    canon = recs[min(canon_hosts)]
    b = recs[blamed[0]]
    idx, entry = _first_divergence(canon["schedule"], b["schedule"])
    if entry is None and b["site"] != canon["site"]:
        entry = (f"<entrypoint order diverged: dispatching "
                 f"{b['site']} while peers dispatch {canon['site']}>")
    if entry is None:
        # identical schedules but diverging rolling hash: an EARLIER
        # round diverged without being caught (e.g. a peer timed out)
        entry = "<dispatch-sequence hash diverged at an earlier round>"
    return {"host": b["host"], "hosts": blamed, "site": b["site"],
            "expected_site": canon["site"], "index": idx,
            "collective": entry, "fingerprint": b["fingerprint"],
            "expected_fingerprint": canon["fingerprint"]}


def _mismatch_error(rec):
    return CollectiveScheduleMismatchError(
        f"collective-schedule mismatch at {rec['site']!r}: host(s) "
        f"{rec['hosts']} diverge from the cohort — first divergent "
        f"collective (position {rec['index']}): {rec['collective']}",
        host=rec["host"], site=rec["site"],
        first_divergent_collective=rec["collective"], index=rec["index"])


class _Verifier:
    """Cross-host schedule verifier over the coordination store.

    One verify round per entrypoint program: fold the fingerprint into
    the rolling dispatch-sequence hash, publish
    ``/commcheck/<epoch>/v<index>/<host>``, gather the cohort's records
    at the same index, and compare. The per-index rendezvous catches
    ORDER divergence (host A verifying engine.step while host B
    verifies engine.eval lands both at the same index with different
    sites); the rolling hash catches divergence that slipped an earlier
    round. A peer that never arrives is a crash/wedge — the watchdog's
    jurisdiction — so a gather timeout records a counter and returns
    rather than mis-typing it as a schedule divergence."""

    def __init__(self, store, host, world_size, epoch=0, timeout=None):
        self.store = store
        self.host = str(host)
        self.world_size = int(world_size)
        self.epoch = int(epoch)
        self.timeout = float(timeout) if timeout is not None \
            else _env_float(_ENV_TIMEOUT, 30.0)
        self._mu = threading.Lock()   # raw: analysis self-guard
        self._rolling = hashlib.sha256()
        self._index = 0
        self._seen = set()
        self._pending = None          # cached mismatch record

    def prefix(self):
        return f"{STORE_PREFIX}/{self.epoch}"

    def _mismatch_key(self):
        return f"{self.prefix()}/mismatch"

    def peek_mismatch(self):
        """The cohort's published mismatch record (or a locally raised
        one), as a typed error — None when the cohort is clean. Never
        raises: pollers (the watchdog blame upgrade) call this from
        sweep threads."""
        with self._mu:
            if self._pending is not None:
                return _mismatch_error(self._pending)
        try:
            raw = self.store.get_nowait(self._mismatch_key())
        except Exception:  # tpu-lint: disable=TL007 — store teardown
            return None    # races the sweep thread; stay quiet
        if raw is None:
            return None
        rec = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        with self._mu:
            self._pending = rec
        return _mismatch_error(rec)

    def verify(self, prog):
        """One verify round for `prog`; raises the typed mismatch error
        when the cohort diverges (on every host). Idempotent per
        program key — only the FIRST dispatch of each entrypoint
        program pays the round trip."""
        if self.world_size <= 1 or self.store is None:
            return
        with self._mu:
            if prog.key in self._seen:
                return
            self._seen.add(prog.key)
            self._rolling.update(prog.fingerprint.encode())
            rolling = self._rolling.hexdigest()
            idx = self._index
            self._index += 1
        rec = {"host": self.host, "site": prog.site, "program": prog.key,
               "fingerprint": prog.fingerprint, "rolling": rolling,
               "schedule": list(prog.schedule)}
        round_prefix = f"{self.prefix()}/v{idx}/"
        self.store.set(round_prefix + self.host,
                       json.dumps(rec, sort_keys=True))
        deadline = time.monotonic() + self.timeout
        while True:
            found = self.peek_mismatch()
            if found is not None:
                _registry.bump("mismatches")
                raise found
            ks = self.store.keys(round_prefix)
            if len(ks) >= self.world_size:
                break
            if time.monotonic() > deadline:
                _registry.bump("verify_timeouts")
                return
            time.sleep(0.02)
        recs = {}
        for k in sorted(ks):
            raw = self.store.get_nowait(k)
            if raw is None:
                continue
            r = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
            recs[r["host"]] = r
        mm = _blame(recs)
        if mm is None:
            _registry.bump("verified")
            return
        try:
            self.store.set(self._mismatch_key(),
                           json.dumps(mm, sort_keys=True))
        except Exception:  # tpu-lint: disable=TL007 — publish is best-
            pass           # effort; the local raise happens regardless
        with self._mu:
            self._pending = mm
        _registry.bump("mismatches")
        raise _mismatch_error(mm)


_verifier = None


def attach_store(store, host, world_size, epoch=0, timeout=None):
    """Arm the cross-host verifier (idempotent per attach): called by
    ``init_parallel_env`` when the auditor is enabled and a coordination
    store exists. `epoch` namespaces the keyspace per spawn life
    (``PADDLE_RESTART_EPOCH``), so an elastic relaunch re-verifies the
    whole cohort under fresh keys."""
    global _verifier
    _verifier = _Verifier(store, host, world_size, epoch=epoch,
                          timeout=timeout)
    return _verifier


def detach_store():
    global _verifier
    _verifier = None


def verifier():
    return _verifier


def pending_mismatch():
    """A published-or-raised cohort mismatch as a typed error, or None.
    The TrainWatchdog consults this before blaming a wedge as merely
    "stalled" — a pending mismatch upgrades the blame to the divergent
    host + collective."""
    v = _verifier
    if v is None:
        return None
    return v.peek_mismatch()


# ---------------------------------------------------------------------------
# report / ratchet surface
# ---------------------------------------------------------------------------

def schedules():
    return _registry.schedules()


def errors():
    return _registry.errors()


def report():
    return _registry.report()


def load_baseline(path):
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "schedules" not in data:
        raise ValueError(f"{path}: not a commcheck baseline "
                         "(missing 'schedules')")
    return data


def write_baseline(path, schedules_):
    """Deterministic (sorted-keys, newline-terminated) baseline dump.
    Unlike the count ratchets this freezes the full schedule per
    program, so a later diff can NAME the first divergent collective
    instead of just counting findings."""
    data = {"version": 1, "tool": "commcheck",
            "schedules": {k: {"site": v["site"],
                              "fingerprint": v["fingerprint"],
                              "collectives": list(v["collectives"])}
                          for k, v in schedules_.items()}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def new_schedules(current, baseline_schedules):
    """{``site::commcheck``: [messages]} for programs whose schedule
    departs from the baseline — a changed fingerprint names the first
    divergent collective tuple; a program with no baseline entry fails
    until ``--write-baseline`` ratchets it (a silently appearing
    entrypoint is exactly what the auditor exists to catch)."""
    out = {}

    def add(site, msg):
        out.setdefault(f"{site}::{RULE}", []).append(msg)

    for key, prog in sorted(current.items()):
        base = baseline_schedules.get(key)
        site = prog["site"]
        if base is None:
            colls = prog["collectives"]
            head = "; ".join(colls[:3]) or "<no collectives>"
            add(site, f"unbaselined program {key}: {len(colls)} "
                      f"collective(s) [{head}{'; ...' if len(colls) > 3 else ''}]"
                      f" — ratchet with --write-baseline")
            continue
        if base["fingerprint"] == prog["fingerprint"]:
            continue
        idx, entry = _first_divergence(base["collectives"],
                                       prog["collectives"])
        add(site, f"schedule of {key} diverged from baseline at "
                  f"position {idx}: {entry} (baseline has "
                  f"{base['collectives'][idx] if idx is not None and idx < len(base['collectives']) else '<end-of-schedule>'})")
    return out
