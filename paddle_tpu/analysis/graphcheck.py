"""paddle_tpu.analysis.graphcheck — the graph auditor.

The analysis family covers what we *wrote* (tracelint, pure AST) and what
we *ran* (tpu-san, runtime probes) — this module audits what XLA actually
**compiled**. It statically walks the ClosedJaxpr and (when available)
the lowered/compiled HLO of every framework entrypoint — engine
train/eval steps, AOT bucket executables (`jit/aot.compile_jit` /
`compile_batched`), exported `TranslatedLayer` calls, `DecodeEngine`
prefill/decode steps — and emits site-keyed findings for graph-level
properties no source lint or runtime probe can see:

* **GC001 unexpected-collective** — collective ops (all-gather,
  all-reduce, reduce-scatter, all-to-all, collective-permute) in a graph
  whose *declared* placement (the `AxisRules`-resolved specs the
  entrypoint was compiled with) uses no sharded mesh axis, or an
  all-gather materializing the FULL value of a parameter the placement
  declared sharded (the rule table failed: "all-gather-everything").
* **GC002 full-replication** — a large operand (default ≥ 16 MiB,
  ``PADDLE_TPU_GRAPHCHECK_REPL_MB``) declared fully replicated on a mesh
  that offers a model-sharding axis (fsdp/tp/mp/sharding/expert) with
  size > 1 — silent replication where sharding was configured.
* **GC003 conv-layout-change** — a layout ``transpose``/``copy`` inside
  a conv/pool region of the jaxpr (within a few def-use hops of a
  `conv_general_dilated`/`reduce_window`): the NHWC enforcement guard —
  no layout changes smuggled into the conv stack.
* **GC004 host-transfer** — a device-to-host transfer compiled INTO the
  graph: callback primitives (`pure_callback`/`io_callback`/
  `debug_callback`) in the jaxpr, or infeed/outfeed in the HLO.
* **GC005 donation-unaliased** — an argument declared donated whose
  buffers do NOT appear in the executable's input-output aliasing table:
  the donation silently bought nothing (the static complement of
  tpu-san's runtime use-after-donate guard; catchable on the CPU mesh
  where the runtime bug would only crash on TPU).
* **GC006 memory-watermark** — an estimated live-memory high-water mark
  per entrypoint (liveness scan over the jaxpr), ratcheted per site
  through the baseline (regression slack
  ``PADDLE_TPU_GRAPHCHECK_MEM_SLACK``, default 0.25) and optionally
  budgeted (``PADDLE_TPU_GRAPHCHECK_MEM_MB``).
* **GC000 audit-error** — the auditor itself failed on an entrypoint
  (never baselined silently; mirrors tracelint's TL000).

Opt-in via ``PADDLE_TPU_GRAPHCHECK=1`` (or :func:`enable`) with the
established zero-overhead-off discipline: every framework hook reduces
to one module-flag check when off. When on, the compile paths call
:func:`audit_executable` — reusing the lowered/compiled objects they
already built where possible (the engine pays one extra AOT
lower+compile per cold entrypoint, documented in
docs/static_analysis.md).

Findings are keyed **site-wise and line-number-free**
(``<site>::<rule>``, e.g. ``engine.step::GC005``) and ratchet through a
checked-in ``.graphcheck_baseline.json`` driven by
``tools/graph_audit.py`` (exit 0 clean / 1 new / 2 usage) — the same
determinism contract as tracelint and tpu-san. Counts export as the
``graphcheck`` collector on the obs registry.
"""
from __future__ import annotations

import os
import re
import threading

__all__ = [
    "RULES", "Finding", "enable", "disable", "enabled", "reset",
    "audit_executable", "findings", "counts_by_key", "watermarks",
    "report", "assert_clean", "load_baseline", "write_baseline",
    "new_counts", "new_watermarks", "jaxpr_watermark",
    "params_bytes_per_chip", "GraphCheckError",
    "OBS_COLLECTOR",
]

_ENV = "PADDLE_TPU_GRAPHCHECK"
_ENV_REPL_MB = "PADDLE_TPU_GRAPHCHECK_REPL_MB"
_ENV_GATHER_BYTES = "PADDLE_TPU_GRAPHCHECK_GATHER_MIN_BYTES"
_ENV_MEM_MB = "PADDLE_TPU_GRAPHCHECK_MEM_MB"
_ENV_MEM_SLACK = "PADDLE_TPU_GRAPHCHECK_MEM_SLACK"

RULES = {
    "GC000": "audit-error: the auditor failed on this entrypoint",
    "GC001": "unexpected collective vs the declared placement",
    "GC002": "large operand fully replicated on a model-sharding mesh",
    "GC003": "layout transpose/copy inside a conv/pool region",
    "GC004": "device-to-host transfer compiled into the graph",
    "GC005": "donation declared but absent from input-output aliasing",
    "GC006": "estimated live-memory watermark over budget/ratchet",
}

#: obs-registry collector name (docs/observability.md)
OBS_COLLECTOR = "graphcheck"

#: per-key cap on stored Finding exemplars (counts stay exact)
_MAX_SAMPLES = 5

#: mesh axes whose presence (size > 1) declares a model-sharding intent —
#: replicating a large operand there is *accidental* (GC002); a dp-only
#: mesh replicates parameters by design and is exempt
MODEL_AXES = ("fsdp", "tp", "mp", "sharding", "expert")

#: HLO collective kinds GC001 recognizes
_COLLECTIVES = ("all-gather", "all-reduce", "reduce-scatter",
                "all-to-all", "collective-permute", "collective-broadcast")

#: jaxpr primitives that anchor a conv/pool region (GC003)
_CONV_ANCHORS = {
    "conv_general_dilated", "reduce_window", "reduce_window_max",
    "reduce_window_min", "reduce_window_sum", "select_and_scatter_add",
}

#: elementwise/shape prims a layout change can hide behind without leaving
#: the conv region (GC003 proximity hops)
_PASSTHROUGH = {
    "add", "sub", "mul", "div", "max", "min", "neg", "abs", "exp", "log",
    "tanh", "logistic", "rsqrt", "sqrt", "sign", "integer_pow", "pow",
    "select_n", "convert_element_type", "broadcast_in_dim", "reshape",
    "squeeze", "expand_dims", "custom_jvp_call", "custom_vjp_call",
    "custom_vjp_call_jaxpr", "pjit", "clamp", "ge", "gt", "le", "lt",
}

#: jaxpr primitives that ARE host transfers (GC004)
_HOST_PRIMS = {"pure_callback", "io_callback", "debug_callback", "infeed",
               "outfeed"}

#: GC003 def-use proximity (hops through _PASSTHROUGH prims)
_CONV_HOPS = 3

_off_values = ("", "0", "false", "off", "no")


def _env_on(name, default=""):
    return os.environ.get(name, default).strip().lower() not in _off_values


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


_enabled = _env_on(_ENV)


class GraphCheckError(RuntimeError):
    """Raised by assert_clean when the auditor holds findings."""


class Finding:
    """One auditor hit. `key` is the baseline identity — site and rule
    only, no line numbers, no instance ids — so the ratchet never churns
    when code moves."""

    __slots__ = ("rule", "site", "message")

    def __init__(self, rule, site, message):
        self.rule = rule
        self.site = site
        self.message = message

    @property
    def key(self):
        return f"{self.site}::{self.rule}"

    def to_dict(self):
        return {"rule": self.rule, "site": self.site,
                "message": self.message}

    def __repr__(self):
        return f"[{self.rule}] {self.site}: {self.message}"


class _Registry:
    """Global recorder. Guarded by a RAW threading.Lock on purpose (the
    analysis recorders must not observe themselves through lockcheck)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._counts = {}       # finding key -> exact count
        self._samples = {}      # finding key -> [Finding] (capped)
        self._watermarks = {}   # site -> max estimated live bytes
        self.counters = {"audits": 0, "compiled_audits": 0,
                         "collectives_seen": 0}

    def record(self, rule, site, message):
        f = Finding(rule, site, message)
        with self._mu:
            self._counts[f.key] = self._counts.get(f.key, 0) + 1
            samples = self._samples.setdefault(f.key, [])
            if len(samples) < _MAX_SAMPLES:
                samples.append(f)
        return f

    def bump(self, name, n=1):
        """Counter increment under the registry lock: concurrent audits
        (decode step-pool thread vs serving workers) must not lose
        updates or race reset()'s dict replacement."""
        with self._mu:
            self.counters[name] = self.counters.get(name, 0) + n

    def note_watermark(self, site, nbytes):
        with self._mu:
            prev = self._watermarks.get(site, 0)
            if nbytes > prev:
                self._watermarks[site] = int(nbytes)

    def findings(self):
        with self._mu:
            return [f for ss in self._samples.values() for f in ss]

    def counts_by_key(self):
        with self._mu:
            return dict(self._counts)

    def watermarks(self):
        with self._mu:
            return dict(self._watermarks)

    def reset(self):
        with self._mu:
            self._counts = {}
            self._samples = {}
            self._watermarks = {}
            self.counters = {k: 0 for k in self.counters}

    def report(self):
        with self._mu:
            return {
                "counts": dict(self._counts),
                "findings": [f.to_dict() for ss in self._samples.values()
                             for f in ss],
                "by_rule": {
                    r: sum(n for k, n in self._counts.items()
                           if k.endswith("::" + r)) for r in RULES},
                "watermarks": dict(self._watermarks),
                "counters": dict(self.counters),
            }


_registry = _Registry()


def registry():
    return _registry


def _obs_collect():
    rep = _registry.report()
    out = {"enabled": int(_enabled),
           "findings": sum(rep["counts"].values()),
           "sites_watermarked": len(rep["watermarks"])}
    out.update({r.lower(): n for r, n in rep["by_rule"].items()})
    out.update(rep["counters"])
    return out


def enable():
    """Turn the auditor on (hooks audit on their next cold compile) and
    register the ``graphcheck`` obs collector."""
    global _enabled
    _enabled = True
    try:
        from ..obs.metrics import registry as _obs
        _obs().register_collector(OBS_COLLECTOR, _obs_collect)
    except Exception:  # tpu-lint: disable=TL007 — obs is optional here:
        pass           # the auditor must work without the registry


def disable():
    global _enabled
    _enabled = False
    try:
        from ..obs.metrics import registry as _obs
        _obs().unregister_collector(OBS_COLLECTOR)
    except Exception:  # tpu-lint: disable=TL007 — symmetric with enable
        pass


def enabled():
    return _enabled


def reset():
    """Clear all recorded state (the enable flag stays)."""
    _registry.reset()


if _enabled:
    enable()     # env asked: register the collector at import


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def _inner_jaxprs(eqn):
    """Sub-jaxprs of one eqn (pjit/scan/cond/custom_* bodies)."""
    out = []
    for v in eqn.params.values():
        vs = v if isinstance(v, (list, tuple)) else (v,)
        for q in vs:
            inner = getattr(q, "jaxpr", None)
            if inner is None:
                continue
            # ClosedJaxpr (scan/pjit params) or raw Jaxpr (custom_jvp)
            out.append(_unwrap(inner))
    return out


def _unwrap(jaxpr):
    """Raw Jaxpr behind a ClosedJaxpr (which forwards .eqns but not the
    var lists the liveness scan needs)."""
    inner = getattr(jaxpr, "jaxpr", None)
    return inner if inner is not None and hasattr(inner, "eqns") else jaxpr


def _walk_jaxprs(jaxpr):
    """Yield every (sub)jaxpr, outermost first."""
    stack = [_unwrap(jaxpr)]
    while stack:
        j = stack.pop()
        yield j
        for e in j.eqns:
            stack.extend(_inner_jaxprs(e))


def _aval_bytes(aval):
    try:
        return int(aval.size) * int(aval.dtype.itemsize)
    except (AttributeError, TypeError):
        return 0


def _prim_name(eqn):
    return eqn.primitive.name


# -- GC003: layout transposes inside conv/pool regions ----------------------

#: call-like prims GC003 inlines so def-use chains survive the op
#: registry's per-op jit boundaries (every framework op traces as its
#: own pjit eqn — without inlining, a transpose and the conv it feeds
#: never share a jaxpr)
_CALL_PRIMS = {"pjit", "custom_jvp_call", "custom_vjp_call",
               "custom_vjp_call_jaxpr", "remat", "checkpoint",
               "closed_call", "core_call"}

_MAX_INLINE_DEPTH = 12


def _is_literal(v):
    return type(v).__name__ == "Literal"


def _inline_units(jaxpr):
    """Flatten into def-use 'units': lists of
    ``(prim_name, in_reps, out_reps, eqn)`` with call-like prims inlined
    (inner vars aliased onto the call boundary vars). scan/cond/while
    bodies become separate units — no cross-iteration chains."""
    roots = [_unwrap(jaxpr)]
    units = []
    while roots:
        root = roots.pop()
        alias = {}
        flat = []

        def rep(v, _alias=alias):
            while v in _alias:
                v = _alias[v]
            return v

        def walk(j, depth, _alias=alias, _flat=flat):
            for e in j.eqns:
                name = _prim_name(e)
                inner = _inner_jaxprs(e)
                if name in _CALL_PRIMS and len(inner) == 1 and \
                        depth < _MAX_INLINE_DEPTH:
                    ij = inner[0]
                    for iv, ov in zip(ij.invars, e.invars):
                        if not _is_literal(ov):
                            _alias[iv] = ov
                    walk(ij, depth + 1)
                    for outer_ov, inner_ov in zip(e.outvars, ij.outvars):
                        if not _is_literal(inner_ov):
                            _alias[outer_ov] = inner_ov
                    continue
                if inner:
                    roots.extend(inner)
                ins = [rep(v) for v in e.invars if not _is_literal(v)]
                outs = [rep(v) for v in e.outvars]
                _flat.append((name, ins, outs, e))

        walk(root, 0)
        units.append(flat)
    return units


def _conv_layout_findings(jaxpr):
    """(message,) per transpose/copy eqn within _CONV_HOPS def-use hops
    of a conv/pool anchor, over the call-inlined units."""
    out = []
    for unit in _inline_units(jaxpr):
        anchor_set = {i for i, (name, *_r) in enumerate(unit)
                      if name in _CONV_ANCHORS}
        if not anchor_set:
            continue
        producer = {}    # rep var -> eqn index
        consumers = {}   # rep var -> [eqn index]
        for i, (_n, ins, outs, _e) in enumerate(unit):
            for v in outs:
                producer[v] = i
            for v in ins:
                consumers.setdefault(v, []).append(i)

        def _reaches_anchor(start_idx, forward, _unit=unit,
                            _anchor=anchor_set, _prod=producer,
                            _cons=consumers):
            seen = {start_idx}
            frontier = [start_idx]
            for _ in range(_CONV_HOPS):
                nxt = []
                for i in frontier:
                    _n, ins, outs, _e = _unit[i]
                    steps = [c for v in outs for c in _cons.get(v, ())] \
                        if forward else \
                        [_prod[v] for v in ins if v in _prod]
                    for s in steps:
                        if s in seen:
                            continue
                        if s in _anchor:
                            return True
                        seen.add(s)
                        if _unit[s][0] in _PASSTHROUGH:
                            nxt.append(s)
                frontier = nxt
            return False

        for i, (name, _ins, _outs, e) in enumerate(unit):
            if name not in ("transpose", "copy"):
                continue
            if _reaches_anchor(i, forward=True) or \
                    _reaches_anchor(i, forward=False):
                aval = e.outvars[0].aval if e.outvars else None
                perm = e.params.get("permutation")
                desc = f" permutation={tuple(perm)}" if perm is not None \
                    else ""
                shape = tuple(getattr(aval, "shape", ()))
                out.append(
                    f"layout `{name}`{desc} -> {shape} within "
                    f"{_CONV_HOPS} def-use hops of a conv/pool op — a "
                    f"layout change smuggled into the conv stack (keep "
                    f"the stack NHWC end-to-end)")
    return out


# -- GC004: host transfers --------------------------------------------------

def _host_transfer_findings(jaxpr, hlo_text):
    out = []
    for j in _walk_jaxprs(jaxpr):
        for e in j.eqns:
            name = _prim_name(e)
            if name in _HOST_PRIMS or name.endswith("_callback"):
                out.append(
                    f"`{name}` primitive compiled into the graph — every "
                    f"dispatch round-trips to the host")
    if hlo_text:
        for kind in ("outfeed", "infeed"):
            n = len(re.findall(rf"\b{kind}\(", hlo_text))
            if n:
                out.append(f"{n} `{kind}` op(s) in the compiled HLO")
    return out


# -- GC006: live-memory watermark -------------------------------------------

def jaxpr_watermark(jaxpr):
    """Estimated live-memory high-water mark (bytes) of a (Closed)Jaxpr:
    a liveness scan over the eqn sequence — inputs/consts live from the
    start, each eqn's outputs become live at the eqn, operands die after
    their last use, outvars live to the end. Sub-jaxpr watermarks (scan/
    cond/pjit bodies) stack on top of the live set at their eqn. An
    estimate (XLA fusion/rematerialization moves the real number), but a
    deterministic one — which is what a ratchet needs."""
    j = _unwrap(jaxpr)
    is_var = lambda v: type(v).__name__ != "Literal"  # noqa: E731
    last_use = {}
    for i, e in enumerate(j.eqns):
        for v in e.invars:
            if is_var(v):
                last_use[v] = i
    live_forever = set(v for v in j.outvars if is_var(v))
    live = {}
    for v in list(j.invars) + list(j.constvars):
        live[v] = _aval_bytes(v.aval)
    peak = sum(live.values())
    for i, e in enumerate(j.eqns):
        for v in e.outvars:
            live[v] = _aval_bytes(v.aval)
        here = sum(live.values())
        inner = max((jaxpr_watermark(sj) for sj in _inner_jaxprs(e)),
                    default=0)
        peak = max(peak, here + inner)
        for v in list(e.invars) + list(e.outvars):
            if is_var(v) and last_use.get(v) == i and v not in live_forever:
                live.pop(v, None)
    return peak


def params_bytes_per_chip(param_avals, param_specs, mesh):
    """Estimated per-chip residency (bytes) of the entrypoint's declared
    parameter/state set: each aval's bytes scaled by its spec's shard
    fraction on `mesh`. The jaxpr watermark above is GLOBAL logical bytes
    — avals don't shrink when a tensor shards — so the fsdp memory story
    ("params + optimizer state hold ~1/N per chip") needs this sibling
    number. Deterministic given (avals, specs, mesh), which is what the
    per-site GC006 ratchet requires; recorded under ``<site>::params``."""
    from ..sharding import shard_fraction

    total = 0.0
    for n, aval in param_avals.items():
        spec = param_specs.get(n)
        frac = shard_fraction(spec, mesh) if spec is not None else 1.0
        total += _aval_bytes(aval) * frac
    return int(total)


# -- GC001 / GC002 helpers ---------------------------------------------------

def _spec_axes(spec):
    """Mesh-axis names a PartitionSpec(-like) references."""
    axes = set()
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        for a in ((entry,) if isinstance(entry, str) else tuple(entry)):
            axes.add(a)
    return axes


def _shardings_leaves(in_shardings):
    """Flat NamedSharding-ish leaves of an in_shardings pytree."""
    if in_shardings is None:
        return []
    import jax

    leaves, _ = jax.tree_util.tree_flatten(
        in_shardings, is_leaf=lambda x: hasattr(x, "spec"))
    return [s for s in leaves if hasattr(s, "spec")]


_HLO_COLLECTIVE_RE = re.compile(
    r"=\s+(?:\()?([a-z0-9]+)\[([0-9,]*)\][^ ]*\s+"
    r"(all-gather|all-reduce|reduce-scatter|all-to-all|collective-permute|"
    r"collective-broadcast)\(")


def _hlo_collectives(hlo_text):
    """[(kind, dtype, dims)] for every collective op in compiled HLO."""
    out = []
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text or ""):
        dtype, dims, kind = m.group(1), m.group(2), m.group(3)
        shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
        out.append((kind, dtype, shape))
    return out


_HLO_DTYPES = {
    "float32": "f32", "float16": "f16", "bfloat16": "bf16",
    "float64": "f64", "int32": "s32", "int64": "s64", "int16": "s16",
    "int8": "s8", "uint32": "u32", "uint8": "u8", "bool": "pred",
}


def _hlo_dtype(dtype):
    return _HLO_DTYPES.get(str(dtype), str(dtype))


_ALIAS_PARAM_RE = re.compile(r"\(\s*(\d+)\s*,\s*\{")


def _aliased_params(hlo_text):
    """Parameter indices in the compiled module's input_output_alias
    table (``input_output_alias={ {0}: (2, {}, may-alias), ... }`` —
    nested braces, so a balanced scan rather than a lazy regex)."""
    marker = "input_output_alias={"
    start = (hlo_text or "").find(marker)
    if start < 0:
        return set()
    i = start + len(marker)
    depth = 1
    while i < len(hlo_text) and depth:
        c = hlo_text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
        i += 1
    body = hlo_text[start + len(marker): i - 1]
    return {int(g) for g in _ALIAS_PARAM_RE.findall(body)}


# ---------------------------------------------------------------------------
# the audit
# ---------------------------------------------------------------------------

def audit_executable(site, *, jit_obj=None, args=None, fn=None,
                     lowered=None, compiled=None, mesh=None,
                     axes_specs=None, in_shardings=None, param_avals=None,
                     param_specs=None, expect_sharded_params=False):
    """Audit one framework entrypoint; returns the findings recorded.

    Two call shapes:

    * ``audit_executable(site, jit_obj=jitted, args=(...))`` — the
      auditor traces, lowers and compiles itself (one extra AOT compile;
      the engine's cold path, opt-in only).
    * ``audit_executable(site, fn=f, args=avals, lowered=l, compiled=c)``
      — the aot compile paths hand over the objects they already built;
      only one extra (cheap) trace for the jaxpr.

    Context: `mesh` + `axes_specs`/`in_shardings` declare the intended
    placement (GC001/GC002); `param_avals`+`param_specs` name parameters
    for the full-gather check, armed by `expect_sharded_params=True`
    (serving/TP entrypoints, where parameters must STAY sharded — fsdp
    training gathers in-graph by design and passes False).

    Never raises: an auditor failure records a GC000 finding (the
    entrypoint still runs; the ratchet surfaces the breakage).
    """
    found = []
    _registry.bump("audits")
    try:
        import jax

        # ---- jaxpr ----------------------------------------------------
        if jit_obj is not None:
            traced = jit_obj.trace(*args)
            jaxpr = traced.jaxpr
            if lowered is None:
                lowered = traced.lower()
        else:
            jaxpr = jax.jit(fn).trace(*args).jaxpr
        hlo_text = ""
        if compiled is None and lowered is not None:
            compiled = lowered.compile()
        if compiled is not None:
            _registry.bump("compiled_audits")
            try:
                hlo_text = compiled.as_text()
            except Exception:  # tpu-lint: disable=TL007 — some backends
                hlo_text = ""  # cannot render text; jaxpr rules still run

        def rec(rule, msg):
            found.append(_registry.record(rule, site, msg))

        # ---- GC003 / GC004 / GC006 (jaxpr) ----------------------------
        for msg in _conv_layout_findings(jaxpr):
            rec("GC003", msg)
        for msg in _host_transfer_findings(jaxpr, hlo_text):
            rec("GC004", msg)
        watermark = jaxpr_watermark(jaxpr)
        _registry.note_watermark(site, watermark)
        if param_avals and param_specs is not None and mesh is not None:
            # per-chip param/state residency rides the same watermark
            # ratchet under its own site key (see params_bytes_per_chip)
            _registry.note_watermark(
                site + "::params",
                params_bytes_per_chip(param_avals, param_specs, mesh))
        budget_mb = _env_float(_ENV_MEM_MB, 0.0)
        if budget_mb and watermark > budget_mb * (1 << 20):
            rec("GC006",
                f"estimated live-memory watermark {watermark} bytes "
                f"exceeds the {budget_mb} MiB budget "
                f"({_ENV_MEM_MB})")

        # ---- declared placement context -------------------------------
        specs = list(axes_specs or ())
        for sh in _shardings_leaves(in_shardings):
            specs.append(sh.spec)
            if mesh is None:
                mesh = getattr(sh, "mesh", None)
        mesh_sizes = dict(mesh.shape) if mesh is not None else {}
        declared_axes = set()
        for s in specs:
            declared_axes |= {a for a in _spec_axes(s)
                              if mesh_sizes.get(a, 1) > 1}

        # ---- GC001: collectives vs declared placement -----------------
        colls = _hlo_collectives(hlo_text)
        _registry.bump("collectives_seen", len(colls))
        if colls and not declared_axes:
            by_kind = {}
            for kind, dtype, shape in colls:
                by_kind.setdefault(kind, []).append((dtype, shape))
            for kind, insts in sorted(by_kind.items()):
                rec("GC001",
                    f"{len(insts)} `{kind}` op(s) (e.g. "
                    f"{insts[0][0]}{list(insts[0][1])}) in a graph whose "
                    f"declared placement is fully replicated — no rule "
                    f"resolved a sharded axis, yet the compiled program "
                    f"communicates")
        if expect_sharded_params and param_avals and param_specs:
            gather_min = int(_env_float(_ENV_GATHER_BYTES, 4096))
            sharded_full = {}
            for n, aval in param_avals.items():
                s = param_specs.get(n)
                if s is None or not _spec_axes(s):
                    continue
                if _aval_bytes(aval) < gather_min:
                    continue
                key = (_hlo_dtype(aval.dtype), tuple(aval.shape))
                sharded_full.setdefault(key, n)
            for kind, dtype, shape in colls:
                if kind != "all-gather":
                    continue
                n = sharded_full.get((dtype, shape))
                if n is not None:
                    rec("GC001",
                        f"all-gather materializes the FULL value "
                        f"{dtype}{list(shape)} of parameter '{n}' that the "
                        f"placement declared sharded "
                        f"({tuple(param_specs[n])}) — the rule table "
                        f"failed; the parameter replicates at every call")

        # ---- GC002: accidental full replication -----------------------
        model_axes = [a for a in MODEL_AXES if mesh_sizes.get(a, 1) > 1]
        if model_axes:
            repl_min = int(_env_float(_ENV_REPL_MB, 16.0) * (1 << 20))
            operands = []
            if param_avals and param_specs is not None:
                operands = [(n, a, param_specs.get(n))
                            for n, a in param_avals.items()]
            elif in_shardings is not None and args:
                avals = [getattr(a, "aval", a) for a in
                         jax.tree_util.tree_leaves(list(args))]
                shs = _shardings_leaves(in_shardings)
                if len(avals) == len(shs):
                    operands = [(f"operand[{i}]", a, sh.spec)
                                for i, (a, sh) in enumerate(zip(avals, shs))]
            for n, aval, s in operands:
                nbytes = _aval_bytes(aval)
                if nbytes >= repl_min and (s is None or not _spec_axes(s)):
                    rec("GC002",
                        f"operand '{n}' ({nbytes >> 20} MiB) is fully "
                        f"replicated while the mesh offers model-sharding "
                        f"axes {model_axes} — every device holds a full "
                        f"copy")

        # ---- GC005: donation vs input-output aliasing -----------------
        if lowered is not None and compiled is not None:
            ainfo = getattr(lowered, "args_info", None)
            if ainfo is not None:
                aliased = _aliased_params(hlo_text)
                # jax PRUNES unused arguments from the compiled module,
                # shifting HLO parameter numbering — map flat leaf index
                # -> HLO parameter index through kept_var_idx. When the
                # mapping is unavailable, degrade to the unambiguous
                # empty-table case only (never a shifted-index false
                # positive).
                kept = None
                try:
                    kept = lowered._lowering.compile_args.get(
                        "kept_var_idx")
                except Exception:  # tpu-lint: disable=TL007 — private
                    kept = None    # jax surface; degrade, don't break
                param_of = {flat: rank
                            for rank, flat in enumerate(sorted(kept))} \
                    if kept is not None else None
                flat_idx = 0
                for argnum, sub in enumerate(
                        ainfo[0] if isinstance(ainfo, tuple) and
                        len(ainfo) == 2 and isinstance(ainfo[1], dict)
                        else ainfo):
                    leaves = jax.tree_util.tree_leaves(sub)
                    idxs = range(flat_idx, flat_idx + len(leaves))
                    flat_idx += len(leaves)
                    donated = [l for l in leaves
                               if getattr(l, "donated", False)]
                    if not donated:
                        continue
                    if param_of is not None:
                        params = [param_of[i] for i in idxs
                                  if i in param_of]
                        if not params:
                            continue    # arg entirely pruned: unused,
                            #             not an aliasing failure
                        bad = not any(p in aliased for p in params)
                    else:
                        bad = not aliased
                    if bad:
                        rec("GC005",
                            f"argument {argnum} ({len(leaves)} leaves) is "
                            f"declared donated but NONE of its buffers "
                            f"appear in the executable's input-output "
                            f"aliasing — the donation bought nothing "
                            f"(dtype/shape/sharding mismatch between the "
                            f"donated input and every output?)")
    except Exception as e:  # noqa: BLE001 — the auditor must never break
        # the entrypoint it audits; the failure itself becomes a
        # (never-silently-baselined) finding
        found.append(_registry.record(
            "GC000", site, f"auditor failed: {type(e).__name__}: {e}"))
    return found


# ---------------------------------------------------------------------------
# module-level report / ratchet surface
# ---------------------------------------------------------------------------

def findings():
    return _registry.findings()


def counts_by_key():
    return _registry.counts_by_key()


def watermarks():
    return _registry.watermarks()


def report():
    return _registry.report()


def assert_clean():
    """Raise GraphCheckError if any finding was recorded (message embeds
    the exemplars). The fault injector's final verdict."""
    rep = _registry.report()
    total = sum(rep["counts"].values())
    if total:
        lines = [f"  {f['site']} [{f['rule']}]: {f['message']}"
                 for f in rep["findings"]]
        raise GraphCheckError(
            f"graphcheck found {total} finding(s):\n" + "\n".join(lines))
    return rep


def load_baseline(path):
    import json

    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "counts" not in data:
        raise ValueError(f"{path}: not a graphcheck baseline "
                         "(missing 'counts')")
    return data


def write_baseline(path, counts, watermarks=None):
    """Deterministic (sorted-keys, newline-terminated) baseline dump —
    same shape as the tracelint/tpu-san ratchets, plus the per-site
    watermark section GC006 ratchets against."""
    import json

    data = {"version": 1, "tool": "graphcheck", "counts": dict(counts),
            "watermarks": {k: int(v)
                           for k, v in (watermarks or {}).items()}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def new_counts(counts, baseline_counts):
    """{key: (count, baselined)} for keys whose count exceeds the
    baselined count — the ratchet's failing set."""
    return {k: (n, baseline_counts.get(k, 0))
            for k, n in sorted(counts.items())
            if n > baseline_counts.get(k, 0)}


def new_watermarks(current, baseline, slack=None):
    """{site: (bytes, baselined_bytes)} for sites whose estimated
    watermark regressed past the baselined value plus slack (default
    0.25, ``PADDLE_TPU_GRAPHCHECK_MEM_SLACK``). Sites with no baselined
    watermark pass (they enter the ratchet on the next
    ``--write-baseline``)."""
    if slack is None:
        slack = _env_float(_ENV_MEM_SLACK, 0.25)
    out = {}
    for site, cur in sorted(current.items()):
        base = baseline.get(site)
        if base is not None and cur > base * (1.0 + slack):
            out[site] = (int(cur), int(base))
    return out
