"""paddle_tpu.analysis.tracelint — pure-AST trace-safety linter.

JAX-under-trace hazards are silent: a `time.time()` inside a jitted
function is evaluated ONCE at trace time and baked into the graph as a
constant; `np.random.*` likewise freezes a single sample; `bool()/int()/
float()/.item()` on a tracer raises `ConcretizationTypeError` (or, under
`jax.ensure_compile_time_eval`, silently concretizes); mutating a
closed-over list/dict from inside a traced function runs at TRACE time,
not per step, so the mutation happens once and then never again; a bare
`print` prints tracers at trace time instead of values per step
(`jax.debug.print` is the runtime form); unhashable static args and
f-strings over traced values force retraces on every call. None of these
fail loudly in the common path — they corrupt results or silently
recompile. This linter finds them statically.

Rule catalogue (docs/static_analysis.md has one bad/good example each):

  TL001  wall-clock call under trace (`time.time/monotonic/perf_counter`,
         `datetime.now`) — value frozen at trace time
  TL002  host RNG under trace (`np.random.*`, stdlib `random.*`) — sample
         frozen at trace time; use `jax.random` with a threaded key
  TL003  concretizing a likely-traced value (`.item()`, `.tolist()`,
         `bool()/int()/float()` on an expression derived from traced
         arguments) — trace-time error or silent constant-folding
  TL004  `np.*` applied to a likely-traced value — silently falls back to
         host numpy at trace time (constant-folds) or raises; use `jnp.*`
  TL005  mutation of closed-over container state under trace (append/
         update/subscript-store on a free variable) — runs once at trace
         time, not per execution
  TL006  `print` under trace — prints tracers at trace time; use
         `jax.debug.print`
  TL007  swallowed exception: bare `except:` or `except Exception:` /
         `except BaseException:` that neither binds the exception nor
         re-raises — hides real faults (anywhere, not just under trace)
  TL008  unhashable static argument: a list/dict/set literal passed in a
         position declared static via `static_argnums`/`static_argnames`
         — `TypeError: unhashable` at call time
  TL009  f-string interpolating a likely-traced value under trace —
         concretization/retrace hazard (the string is built at trace
         time from the tracer's repr)
  TL010  `time.time()` anywhere (host code included): wall clocks step
         under NTP, so deadline/interval arithmetic built on them can
         jump backwards or fire early/late — use `time.monotonic()`;
         suppress where wall-clock time IS the point (manifest
         timestamps, user-facing dates)
  TL011  raw `NamedSharding(`/`PartitionSpec(` construction outside
         `paddle_tpu/sharding/` — placement has ONE authority (the
         sharding subsystem's factories/rule table); hand-built
         shardings drift out of agreement with it. Legacy sites are
         frozen in the baseline and burn down instead of growing
  TL012  raw `threading.Lock()`/`RLock()`/`Condition()` construction
         outside `paddle_tpu/analysis/` — anonymous locks are invisible
         to lockcheck (no name in the acquisition-order graph, no
         held-across-blocking attribution) and to tpu-san's reports;
         use `analysis.locks.new_lock("subsystem.name")` and friends.
         Legacy sites are frozen in the baseline and burn down

Suppressions: append ``# tpu-lint: disable=TL001`` (comma-separate for
several, or ``disable=all``) to the offending line (for ``except``
clauses: the ``except`` line). Suppressed findings never appear and never
enter the baseline.

Baseline ratchet: existing findings are frozen in
``.tpu_lint_baseline.json`` keyed by ``path::rule::scope`` with a count
(line numbers deliberately excluded so unrelated edits don't churn the
file). A key whose current count exceeds its baselined count fails; a key
at or under it passes. Regenerate with ``tools/tpu_lint.py
--write-baseline`` (sorted keys — diffs stay reviewable).

Everything here is stdlib-`ast` only: no imports of the linted code, no
JAX, safe to run anywhere (including CI boxes with no accelerator).
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize

__all__ = [
    "RULES", "Finding", "lint_source", "lint_file", "lint_paths",
    "iter_python_files", "load_baseline", "write_baseline",
    "new_findings",
]

RULES = {
    "TL000": "file does not parse (never baseline this: fix the syntax)",
    "TL001": "wall-clock call under trace (value frozen at trace time)",
    "TL002": "host RNG under trace (use jax.random with a threaded key)",
    "TL003": "concretizing a likely-traced value",
    "TL004": "np.* applied to a likely-traced value (use jnp.*)",
    "TL005": "mutation of closed-over state under trace (runs once, at "
             "trace time)",
    "TL006": "print under trace (use jax.debug.print)",
    "TL007": "swallowed exception (bare/overbroad except that neither "
             "binds nor re-raises)",
    "TL008": "unhashable literal passed as a static argument",
    "TL009": "f-string over a likely-traced value under trace "
             "(concretization/retrace hazard)",
    "TL010": "wall-clock time.time() for deadline/interval math (NTP "
             "step-fragile; use time.monotonic())",
    "TL011": "raw NamedSharding/PartitionSpec construction outside "
             "paddle_tpu/sharding (use the sharding factories/rule "
             "table)",
    "TL012": "raw threading.Lock/RLock/Condition construction outside "
             "paddle_tpu/analysis (use analysis.locks named "
             "constructors so lockcheck can see the lock)",
}

#: files allowed to construct shardings directly (the authority itself)
_SHARDING_AUTHORITY = "paddle_tpu/sharding/"
#: files allowed to construct raw threading primitives (the lock
#: authority itself: locks.py's off-path constructors, lockcheck's and
#: runtime_san's self-guards, which must never observe themselves)
_LOCK_AUTHORITY = "paddle_tpu/analysis/"

# Decorators / higher-order callers that put the wrapped function under a
# JAX trace. Matched on the trailing dotted components, so `jax.jit`,
# `jit`, `partial(jax.jit, ...)` and `functools.partial(jit, ...)` all
# hit.
_TRACING_NAMES = {
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad",
    "checkpoint", "remat", "custom_jvp", "custom_vjp", "defjvp",
    "linearize", "jvp", "vjp", "make_jaxpr", "eval_shape", "xla_computation",
    "to_static",
}
# Higher-order lax/control-flow callers whose FUNCTION ARGUMENTS are
# traced (the call itself may appear in untraced code).
_TRACING_CALLERS = _TRACING_NAMES | {
    "scan", "while_loop", "cond", "fori_loop", "switch", "map",
    "associative_scan", "custom_root",
}
# Which positional args of a tracing caller are the traced callables
# (everything not listed here takes its function at position 0):
#   while_loop(cond_fun, body_fun, init)   cond(pred, true_fn, false_fn, *)
#   fori_loop(lo, hi, body_fun, init)      switch(index, branches, *)
_CALLABLE_POSITIONS = {
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": (1, 2),
    "switch": (1,),
}

_WALL_CLOCK = {
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("time", "monotonic_ns"), ("time", "perf_counter_ns"),
    ("time", "time_ns"), ("datetime", "now"), ("datetime", "utcnow"),
}
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
    "extendleft", "popleft",
}
_NP_SAFE = {
    # attribute *calls* on np that are trace-safe / shape-only
    "ndim", "shape", "dtype", "result_type", "promote_types", "issubdtype",
    "iinfo", "finfo", "can_cast", "broadcast_shapes",
}

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*disable(?:=|\s*=\s*)([A-Za-z0-9_,\s]+|all)")


class Finding:
    """One lint hit. `key` is the baseline identity: path, rule and
    enclosing scope — no line numbers, so edits elsewhere in the file
    don't invalidate the ratchet."""

    __slots__ = ("rule", "path", "line", "col", "scope", "message")

    def __init__(self, rule, path, line, col, scope, message=""):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.scope = scope
        self.message = message or RULES[rule]

    @property
    def key(self):
        return f"{self.path}::{self.rule}::{self.scope}"

    def to_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "scope": self.scope,
                "message": self.message}

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def __repr__(self):
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


# --------------------------------------------------------------------------
# suppression comments
# --------------------------------------------------------------------------

def _suppressions(source):
    """line (1-based) -> set of suppressed rule ids (or {'all'}).

    Only real COMMENT tokens count — a string literal containing the
    marker text must not silence findings on its line. Callers parse the
    source first, so tokenization is expected to succeed; if it still
    fails we fall back to honoring no suppressions (fail CLOSED: a
    finding too many beats one silently masked)."""
    out = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(t.start[0], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for lineno, text in comments:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        # rule tokens run until the first word that is not rule-shaped:
        # `disable=TL007 deliberate swallow` suppresses TL007 (the plain
        # -word reason must not void the suppression it annotates)
        tokens = [t for t in re.split(r"[\s,]+", m.group(1).strip()) if t]
        if tokens and tokens[0].lower() == "all":
            out[lineno] = {"all"}
            continue
        rules = set()
        for tok in tokens:
            if re.fullmatch(r"[A-Za-z]{2}\d+", tok):
                rules.add(tok.upper())
            else:
                break
        if rules:
            out[lineno] = rules
    return out


def _wallclock_aliases(tree):
    """local name -> dotted wall-clock callable for `from time import
    time [as t]`-style bindings, which call sites reach as a BARE name
    the two-component _WALL_CLOCK match can never see."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if ("time", a.name) in _WALL_CLOCK:
                    out[a.asname or a.name] = f"time.{a.name}"
    return out


def _rng_aliases(tree):
    """local name -> dotted host-RNG callable for `from random import
    random [as r]` / `from numpy.random import rand`-style bindings,
    which call sites reach as a BARE name the `random.`/`np.random.`
    prefix match can never see."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
                "random", "numpy.random"):
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _jax_aliases(tree):
    """Names this module binds to JAX submodules, e.g. `from jax import
    random` / `import jax.random as jrandom` / `import jax.numpy as np`.
    Rules that pattern-match on `random.*` / `np.*` must NOT fire on
    names that actually resolve to jax — that code is already correct."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.") and a.asname:
                    out[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and (node.module == "jax"
                     or node.module.startswith("jax.")):
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def _module_aliases(tree):
    """asname -> real dotted module for `import time as t` /
    `import numpy as n` / `import numpy.random as nr` bindings (plus
    `from datetime import datetime as dt`). Hazard matching is on the
    real module path, so aliased call sites resolve through this first."""
    real = ("time", "datetime", "random", "numpy", "numpy.random")
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname and a.asname != a.name and a.name in real:
                    out[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module == "datetime":
            for a in node.names:
                if a.name == "datetime" and a.asname:
                    out[a.asname] = "datetime"
    return out


def _resolve_module_alias(callee, aliases):
    """'t.time' -> 'time.time' when `import time as t` is in scope."""
    if not callee or not aliases or "." not in callee:
        return callee
    head, rest = callee.split(".", 1)
    real = aliases.get(head)
    return f"{real}.{rest}" if real else callee


def _suppressed(suppress, rule, *lines):
    for ln in lines:
        if ln is None:
            continue
        rules = suppress.get(ln)
        if rules and ("all" in rules or rule in rules):
            return True
    return False


# --------------------------------------------------------------------------
# small AST helpers
# --------------------------------------------------------------------------

def _dotted(node):
    """'jax.lax.scan' for an Attribute/Name chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last(dotted):
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _names_in(node):
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_tracing_callee(dotted):
    """True if a dotted callee name is a trace-inducing higher-order
    caller (jax.jit / lax.scan / jit / partial(jax.jit, ...) handled by
    the caller)."""
    if not dotted:
        return False
    last = _last(dotted)
    if last not in _TRACING_CALLERS:
        return False
    # plain `map`/`cond`/... only count when qualified (lax.map), to
    # avoid flagging builtins; the jit/vmap-style names count bare too.
    if last in ("map", "cond", "switch", "while_loop", "scan",
                "fori_loop") and "." not in dotted:
        return False
    return True


def _tracing_decorator(dec):
    """Does this decorator node put the function under trace?"""
    if isinstance(dec, ast.Call):
        callee = _dotted(dec.func)
        if _last(callee) == "partial":
            return any(_is_tracing_callee(_dotted(a)) for a in dec.args)
        return _is_tracing_callee(callee)
    return _is_tracing_callee(_dotted(dec))


# --------------------------------------------------------------------------
# phase A: find traced regions (functions + lambdas) in a module
# --------------------------------------------------------------------------

class _FuncInfo:
    __slots__ = ("node", "qualname", "called", "traced", "nested_in")

    def __init__(self, node, qualname, nested_in=None):
        self.node = node
        self.qualname = qualname
        self.called = set()     # simple names this function calls
        self.traced = False
        self.nested_in = nested_in  # enclosing _FuncInfo or None


class _Collector(ast.NodeVisitor):
    """Builds the per-module function table, the (name-resolved, same
    module) call graph, and the traced-root set."""

    def __init__(self):
        self.funcs = []             # all _FuncInfo
        self.by_name = {}           # simple name -> [_FuncInfo]
        self.traced_lambdas = []    # Lambda nodes passed to tracing callers
        self._scope = []            # stack of _FuncInfo
        self._class_stack = []
        self._deferred_marks = []   # simple names to resolve post-walk

    # -- defs -------------------------------------------------------------
    def _handle_def(self, node):
        parts = [f.node.name for f in self._scope]
        qual = ".".join(self._class_stack + parts + [node.name])
        info = _FuncInfo(node, qual,
                         nested_in=self._scope[-1] if self._scope else None)
        if any(_tracing_decorator(d) for d in node.decorator_list):
            info.traced = True
        self.funcs.append(info)
        self.by_name.setdefault(node.name, []).append(info)
        self._scope.append(info)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._handle_def(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self._class_stack.append(node.name)
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._class_stack.pop()

    # -- calls ------------------------------------------------------------
    def _mark_callable_arg(self, arg):
        # resolution happens after the walk: the target def may appear
        # later in the module than the call that traces it
        if isinstance(arg, ast.Lambda):
            # pair the lambda with its enclosing qualname NOW (the scope
            # stack is live): scope must stay line-number-free or the
            # baseline key churns whenever code above the lambda moves
            encl = ".".join(self._class_stack
                            + [f.node.name for f in self._scope])
            self.traced_lambdas.append((arg, encl or "<module>"))
        elif isinstance(arg, ast.Name):
            self._deferred_marks.append(arg.id)
        elif isinstance(arg, ast.Attribute):
            self._deferred_marks.append(arg.attr)

    def resolve_marks(self):
        for name in self._deferred_marks:
            for info in self.by_name.get(name, ()):
                info.traced = True

    def visit_Call(self, node):
        callee = _dotted(node.func)
        if self._scope and isinstance(node.func, ast.Name):
            self._scope[-1].called.add(node.func.id)
        if _is_tracing_callee(callee):
            # only CALLABLE positions: data args (scan carry/xs, cond
            # operands) must not taint a same-named module function
            for i in _CALLABLE_POSITIONS.get(_last(callee), (0,)):
                if i < len(node.args):
                    a = node.args[i]
                    if isinstance(a, (ast.List, ast.Tuple)):
                        for el in a.elts:    # switch branch lists
                            self._mark_callable_arg(el)
                    else:
                        self._mark_callable_arg(a)
            for kw in node.keywords:
                if kw.arg in ("f", "fun", "func", "body_fun", "cond_fun"):
                    self._mark_callable_arg(kw.value)
        self.generic_visit(node)


def _propagate(collector):
    """Mark traced: roots + nested defs inside traced fns + same-module
    callees of traced fns (transitively)."""
    changed = True
    while changed:
        changed = False
        for info in collector.funcs:
            if info.traced:
                continue
            if info.nested_in is not None and info.nested_in.traced:
                info.traced = changed = True
                continue
        frontier = [f for f in collector.funcs if f.traced]
        seen = set(id(f) for f in frontier)
        while frontier:
            f = frontier.pop()
            for name in f.called:
                for callee in collector.by_name.get(name, ()):
                    if id(callee) not in seen:
                        callee.traced = True
                        seen.add(id(callee))
                        frontier.append(callee)
                        changed = True
    return [f for f in collector.funcs if f.traced]


# --------------------------------------------------------------------------
# phase B: rule walkers
# --------------------------------------------------------------------------

class _TraceRules(ast.NodeVisitor):
    """Walks ONE traced function/lambda body. Nested defs/lambdas are
    skipped — they are traced regions of their own and get their own
    walk."""

    def __init__(self, path, scope, params, suppress, findings,
                 jax_aliases=None, wall_lines=None, wall_aliases=None,
                 mod_aliases=None, rng_aliases=None):
        self.path = path
        self.scope = scope
        self.tainted = set(params)
        self.local = set(params)
        self.suppress = suppress
        self.findings = findings
        self.jax_aliases = jax_aliases or {}
        self.wall_aliases = wall_aliases or {}
        self.mod_aliases = mod_aliases or {}
        self.rng_aliases = rng_aliases or {}
        # lines with a wall-clock call under trace, SUPPRESSED OR NOT:
        # the TL010 sweep skips them so one acknowledged call never
        # needs a second stacked `disable=TL010`
        self.wall_lines = wall_lines if wall_lines is not None else set()
        self._root = None

    def _is_jax(self, callee):
        return bool(callee) and \
            self.jax_aliases.get(callee.split(".", 1)[0], "").startswith(
                "jax")

    # -- helpers ----------------------------------------------------------
    def _emit(self, rule, node, message=""):
        line = getattr(node, "lineno", None)
        if _suppressed(self.suppress, rule, line,
                       getattr(node, "end_lineno", None)):
            return
        self.findings.append(Finding(
            rule, self.path, line or 0, getattr(node, "col_offset", 0),
            self.scope, message or RULES[rule]))

    def _is_tainted(self, node):
        return bool(_names_in(node) & self.tainted)

    # -- scope fencing -----------------------------------------------------
    def run(self, root_body):
        for stmt in root_body:
            self.visit(stmt)

    def visit_FunctionDef(self, node):
        self.local.add(node.name)   # nested def binds its name locally

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass  # walked separately if itself passed to a tracing caller

    def visit_ClassDef(self, node):
        self.local.add(node.name)

    # -- taint bookkeeping -------------------------------------------------
    def visit_Assign(self, node):
        self.visit(node.value)
        tainted = self._is_tainted(node.value)
        for tgt in node.targets:
            self._check_store(tgt)
            for n in ast.walk(tgt):
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                    self.local.add(n.id)
                    if tainted:
                        self.tainted.add(n.id)

    def visit_AugAssign(self, node):
        self.visit(node.value)
        self._check_store(node.target)
        if isinstance(node.target, ast.Name):
            self.local.add(node.target.id)
            if self._is_tainted(node.value):
                self.tainted.add(node.target.id)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self.visit(node.value)
            if isinstance(node.target, ast.Name):
                self.local.add(node.target.id)
                if self._is_tainted(node.value):
                    self.tainted.add(node.target.id)

    def visit_For(self, node):
        self.visit(node.iter)
        tainted = self._is_tainted(node.iter)
        for n in ast.walk(node.target):
            if isinstance(n, ast.Name):
                self.local.add(n.id)
                if tainted:
                    self.tainted.add(n.id)
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_With(self, node):
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                for n in ast.walk(item.optional_vars):
                    if isinstance(n, ast.Name):
                        self.local.add(n.id)
        for stmt in node.body:
            self.visit(stmt)

    def visit_comprehension_targets(self, node):
        for gen in node.generators:
            for n in ast.walk(gen.target):
                if isinstance(n, ast.Name):
                    self.local.add(n.id)
        self.generic_visit(node)

    visit_ListComp = visit_comprehension_targets
    visit_SetComp = visit_comprehension_targets
    visit_DictComp = visit_comprehension_targets
    visit_GeneratorExp = visit_comprehension_targets

    # -- TL005: closed-over mutation --------------------------------------
    def _check_store(self, tgt):
        if isinstance(tgt, ast.Subscript):
            root = tgt.value
            while isinstance(root, ast.Subscript):
                root = root.value
            name = _dotted(root)
            if name is None:
                return
            head = name.split(".", 1)[0]
            # self/cls are parameters, not closed-over state — same
            # exemption as the mutator-call branch below
            if head not in self.local and head not in ("self", "cls"):
                self._emit("TL005", tgt,
                           f"subscript-store into closed-over `{name}` "
                           "runs at trace time, not per step")

    # -- calls: TL001/2/3/4/6 + TL005 mutator methods ----------------------
    def visit_Call(self, node):
        callee = _resolve_module_alias(_dotted(node.func),
                                       self.mod_aliases)
        last = _last(callee)

        wall = self.wall_aliases.get(callee, callee) if callee else None
        if wall and tuple(wall.split(".")[-2:]) in _WALL_CLOCK:
            if node.lineno:
                self.wall_lines.add(node.lineno)
            self._emit("TL001", node,
                       f"`{callee}()` is evaluated once, at trace time")
        elif callee and (callee.startswith(("np.random.", "numpy.random.",
                                            "random."))
                         or (callee in self.rng_aliases
                             and callee not in self.local)) \
                and not self._is_jax(callee):
            real = self.rng_aliases.get(callee, callee)
            self._emit("TL002", node,
                       f"`{real}()` freezes one host sample into the "
                       "graph; use jax.random")
        elif callee and callee.split(".", 1)[0] in ("np", "numpy") \
                and "." in callee and last not in _NP_SAFE \
                and not callee.split(".", 1)[1].startswith("random") \
                and not self._is_jax(callee):
            if any(self._is_tainted(a) for a in node.args):
                self._emit("TL004", node,
                           f"`{callee}` on a traced value constant-folds "
                           "at trace time or raises; use jnp")
        elif last in ("bool", "int", "float") and callee == last \
                and len(node.args) == 1 and self._is_tainted(node.args[0]):
            self._emit("TL003", node,
                       f"`{last}()` on a traced value concretizes at "
                       "trace time")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("item", "tolist") \
                and self._is_tainted(node.func.value):
            last = node.func.attr
            self._emit("TL003", node,
                       f"`.{last}()` on a traced value concretizes at "
                       "trace time")
        elif callee == "print":
            self._emit("TL006", node)
        elif last in _MUTATORS and isinstance(node.func, ast.Attribute):
            name = _dotted(node.func.value)
            if name is not None:
                head = name.split(".", 1)[0]
                if head not in self.local and head not in ("self", "cls"):
                    self._emit("TL005", node,
                               f"`{name}.{last}(...)` mutates closed-over "
                               "state at trace time")
        self.generic_visit(node)

    # -- TL009: f-strings over traced values -------------------------------
    def visit_JoinedStr(self, node):
        for v in node.values:
            if isinstance(v, ast.FormattedValue) and self._is_tainted(
                    v.value):
                self._emit("TL009", node,
                           "f-string interpolates a traced value "
                           "(concretization/retrace hazard)")
                break
        self.generic_visit(node)


def _swallow_findings(path, tree, suppress, findings):
    """TL007 over the whole module (traced or not)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        overbroad = node.type is None or _last(_dotted(node.type)) in (
            "Exception", "BaseException")
        if isinstance(node.type, ast.Tuple):
            overbroad = any(_last(_dotted(e)) in ("Exception",
                                                  "BaseException")
                            for e in node.type.elts)
        if not overbroad or node.name is not None:
            continue
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            continue
        if _suppressed(suppress, "TL007", node.lineno):
            continue
        findings.append(Finding(
            "TL007", path, node.lineno, node.col_offset, "<module>",
            "bare/overbroad except neither binds nor re-raises the "
            "exception — name the expected type, bind `as e`, or add a "
            "suppression saying what is deliberately swallowed"))


def _wallclock_findings(path, tree, suppress, findings, wall_aliases=None,
                        mod_aliases=None):
    """TL010 over the whole module. Call sites already flagged TL001
    (under trace) are filtered by the caller — one diagnosis per bug."""
    wall_aliases = wall_aliases or {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _resolve_module_alias(_dotted(node.func),
                                       mod_aliases or {})
        if wall_aliases.get(callee, callee) in ("time.time",
                                                "time.time_ns"):
            if not _suppressed(suppress, "TL010", node.lineno):
                findings.append(Finding(
                    "TL010", path, node.lineno, node.col_offset,
                    "<module>"))


_SHARDING_CTORS = {"NamedSharding", "PartitionSpec"}


def _ctor_authority_findings(path, tree, suppress, findings, *, module,
                             ctors, rule, message):
    """Shared skeleton of the construction-authority rules (TL011,
    TL012): flag Call nodes that construct one of `ctors` from `module`.
    Resolves the from-import (with as-aliases, e.g. ``PartitionSpec as
    P`` / ``Lock as L``), module aliases (``import jax.sharding as
    jsh`` / ``import threading as t``) and — for dotted modules —
    ``from <parent> import <sub> [as alias]``. Same-named ctors from
    OTHER modules (``multiprocessing.Lock``) never match: resolution is
    to the real module path. `message` maps a ctor name to the finding
    text. Authority-path exemption is handled by the caller."""
    local = {}      # local callable name -> ctor name
    mod_alias = {}  # alias -> module
    if "." not in module:
        mod_alias[module] = module      # plain `import threading`
    parent, _, sub = module.rpartition(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == module:
                for a in node.names:
                    if a.name in ctors:
                        local[a.asname or a.name] = a.name
            elif parent and node.module == parent:
                # `from jax import sharding [as jsh]` — call sites reach
                # the ctors through the submodule name
                for a in node.names:
                    if a.name == sub:
                        mod_alias[a.asname or a.name] = module
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module and a.asname:
                    mod_alias[a.asname] = module
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = _dotted(node.func)
        if not callee:
            continue
        ctor = local.get(callee)
        if ctor is None and "." in callee:
            head, _, rest = callee.partition(".")
            resolved = f"{mod_alias.get(head, head)}.{rest}" \
                if head in mod_alias else callee
            if resolved.startswith(module + ".") and \
                    resolved.rsplit(".", 1)[-1] in ctors:
                ctor = resolved.rsplit(".", 1)[-1]
        if ctor is None:
            continue
        if _suppressed(suppress, rule, node.lineno):
            continue
        findings.append(Finding(
            rule, path, node.lineno, node.col_offset, "<module>",
            message(ctor)))


def _sharding_ctor_findings(path, tree, suppress, findings):
    """TL011 over the whole module: calls that construct
    jax.sharding.{NamedSharding, PartitionSpec} directly. Files under
    `paddle_tpu/sharding/` are the authority and exempt (handled by the
    caller)."""
    _ctor_authority_findings(
        path, tree, suppress, findings,
        module="jax.sharding", ctors=_SHARDING_CTORS, rule="TL011",
        message=lambda ctor: (
            f"raw `{ctor}(...)` — resolve placement through "
            f"paddle_tpu.sharding (named_sharding/spec/rule table)"))


_LOCK_CTORS = {"Lock", "RLock", "Condition"}


def _lock_ctor_findings(path, tree, suppress, findings):
    """TL012 over the whole module: calls that construct
    ``threading.{Lock, RLock, Condition}`` directly — anonymous to
    lockcheck's acquisition-order graph and to tpu-san.
    `multiprocessing.Lock()` etc. never match. Files under
    `paddle_tpu/analysis/` are the authority and exempt (handled by the
    caller)."""
    _ctor_authority_findings(
        path, tree, suppress, findings,
        module="threading", ctors=_LOCK_CTORS, rule="TL012",
        message=lambda ctor: (
            f"raw `threading.{ctor}(...)` — use `analysis.locks."
            f"new_{ctor.lower()}(\"subsystem.name\")` so lockcheck and "
            f"tpu-san can see and name the lock"))


def _static_spec(keywords):
    """(positions, names) declared static in a jit/partial keyword list."""
    positions, names = set(), set()
    for kw in keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        v = kw.value
        elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        for e in elts:
            if not isinstance(e, ast.Constant):
                continue
            if kw.arg == "static_argnums" and isinstance(e.value, int):
                positions.add(e.value)
            elif kw.arg == "static_argnames" and isinstance(e.value, str):
                names.add(e.value)
    return positions, names


def _static_arg_findings(path, tree, suppress, findings):
    """TL008: list/dict/set literals at positions declared static.

    Two declaration shapes are resolved to the name call sites use:
      g = jax.jit(f, static_argnums=(1,))     ->  calls of `g`
      @partial(jax.jit, static_argnums=(1,))  ->  calls of the def'd name
      def f(...)
    """
    wrapped = {}   # callable name -> (positions, names, is_method)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if _last(_dotted(call.func)) in ("jit", "pjit"):
                pos, names = _static_spec(call.keywords)
                if pos or names:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            wrapped[tgt.id] = (pos, names, False)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if not isinstance(dec, ast.Call):
                    continue
                dlast = _last(_dotted(dec.func))
                if dlast in ("jit", "pjit") or (
                        dlast == "partial" and any(
                            _last(_dotted(a)) in ("jit", "pjit")
                            for a in dec.args)):
                    pos, names = _static_spec(dec.keywords)
                    if pos or names:
                        args = node.args.posonlyargs + node.args.args
                        is_method = bool(args) and \
                            args[0].arg in ("self", "cls")
                        wrapped[node.name] = (pos, names, is_method)
    if not wrapped:
        return
    unhashable = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                  ast.SetComp)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = _dotted(node.func)
        spec = wrapped.get(_last(fname)) if fname else None
        if spec is None:
            continue
        positions, names, is_method = spec
        # a method is called bound (`m.f(...)`) and its static_argnums
        # count `self`; a plain function is called by name. Requiring the
        # shapes to agree both fixes the position bookkeeping and stops
        # unrelated attribute calls that merely SHARE the last name
        # component from matching a wrapped plain function.
        if is_method != isinstance(node.func, ast.Attribute):
            continue
        offset = 1 if is_method else 0
        bad = [a for i, a in enumerate(node.args)
               if i + offset in positions] + \
              [kw.value for kw in node.keywords if kw.arg in names]
        for b in bad:
            if isinstance(b, unhashable) and not _suppressed(
                    suppress, "TL008", node.lineno):
                findings.append(Finding(
                    "TL008", path, b.lineno, b.col_offset, "<module>",
                    f"unhashable literal passed to `{fname}` in a static "
                    "position — TypeError at call time"))


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def lint_source(source, path="<string>"):
    """Lint one source string. Returns a sorted list of Findings."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        # dedicated rule id: reporting this under a real rule would let a
        # baselined finding for the same file::rule::scope key silently
        # absorb the parse error (and the ratchet would also "pass" every
        # finding the broken file can no longer produce)
        return [Finding("TL000", path, e.lineno or 0, 0, "<module>",
                        f"file does not parse: {e.msg}")]
    suppress = _suppressions(source)
    jax_aliases = _jax_aliases(tree)
    wall_aliases = _wallclock_aliases(tree)
    mod_aliases = _module_aliases(tree)
    rng_aliases = _rng_aliases(tree)
    findings = []

    collector = _Collector()
    collector.visit(tree)
    collector.resolve_marks()
    traced = _propagate(collector)

    wall_under_trace = set()
    for info in traced:
        node = info.node
        params = [a.arg for a in (node.args.posonlyargs + node.args.args
                                  + node.args.kwonlyargs)
                  if a.arg not in ("self", "cls")]
        if node.args.vararg:
            params.append(node.args.vararg.arg)
        if node.args.kwarg:
            params.append(node.args.kwarg.arg)
        walker = _TraceRules(path, info.qualname, params, suppress,
                             findings, jax_aliases, wall_under_trace,
                             wall_aliases, mod_aliases, rng_aliases)
        walker.run(node.body)
    for lam, encl in collector.traced_lambdas:
        params = [a.arg for a in lam.args.args]
        walker = _TraceRules(path, f"<lambda in {encl}>", params,
                             suppress, findings, jax_aliases,
                             wall_under_trace, wall_aliases, mod_aliases,
                             rng_aliases)
        walker.visit(lam.body)

    _swallow_findings(path, tree, suppress, findings)
    # TL001 territory (suppressed or not) is excluded from the TL010
    # sweep: the under-trace diagnosis is the more specific one, and a
    # `disable=TL001` must silence that line outright
    tl001_lines = {f.line for f in findings if f.rule == "TL001"} \
        | wall_under_trace
    wall = []
    _wallclock_findings(path, tree, suppress, wall, wall_aliases,
                        mod_aliases)
    findings.extend(f for f in wall if f.line not in tl001_lines)
    _static_arg_findings(path, tree, suppress, findings)
    posix_path = path.replace(os.sep, "/")
    if _SHARDING_AUTHORITY not in posix_path:
        _sharding_ctor_findings(path, tree, suppress, findings)
    if _LOCK_AUTHORITY not in posix_path:
        _lock_ctor_findings(path, tree, suppress, findings)
    return sorted(findings, key=Finding.sort_key)


def lint_file(path, rel=None):
    try:
        # tokenize.open honors PEP 263 coding cookies (valid non-UTF-8
        # source must not crash the ratchet run)
        with tokenize.open(path) as f:
            source = f.read()
    except (UnicodeDecodeError, SyntaxError, ValueError) as e:
        return [Finding("TL000", rel or path, 0, 0, "<module>",
                        f"file cannot be decoded: {e}")]
    return lint_source(source, rel or path)


def iter_python_files(root):
    """Sorted walk of .py files under `root` (deterministic output)."""
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d not in ("__pycache__", ".git"))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths, relative_to=None):
    """Lint files/trees. Paths in findings are made relative to
    `relative_to` (posix separators) so baselines are machine-portable.
    Files reachable from several roots are linted ONCE — double-counting
    would push per-key counts past their own baseline."""
    findings, seen = [], set()
    for root in paths:
        for path in iter_python_files(root):
            real = os.path.realpath(path)
            if real in seen:
                continue
            seen.add(real)
            rel = path
            if relative_to:
                candidate = os.path.relpath(path, relative_to)
                # a target OUTSIDE relative_to would get a '../..'-style
                # key that depends on where the two trees sit relative
                # to each other — keep the absolute path instead
                if not candidate.startswith(os.pardir + os.sep):
                    rel = candidate
            rel = rel.replace(os.sep, "/")
            findings.extend(lint_file(path, rel))
    return sorted(findings, key=Finding.sort_key)


# --------------------------------------------------------------------------
# baseline ratchet
# --------------------------------------------------------------------------

def counts_by_key(findings):
    out = {}
    for f in findings:
        out[f.key] = out.get(f.key, 0) + 1
    return out


def load_baseline(path):
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "counts" not in data:
        raise ValueError(f"{path}: not a tpu-lint baseline "
                         "(missing 'counts')")
    return data["counts"]


def write_baseline(path, findings):
    """Deterministic (sorted-keys, newline-terminated) baseline dump.
    TL000 (parse/decode failure) is never written: baselining it would
    make CI pass on a file that does not parse — and a broken file
    produces ONLY TL000, hiding every real finding it would have."""
    data = {"version": 1, "tool": "tpu_lint",
            "counts": counts_by_key(
                f for f in findings if f.rule != "TL000")}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def new_findings(findings, baseline_counts):
    """Findings at keys whose count exceeds the baselined count. All
    findings at an over-budget key are reported (the linter cannot know
    which individual one is 'new' without line-number churn). TL000 is
    ALWAYS new — a hand-edited baseline entry must not absorb it."""
    current = counts_by_key(findings)
    over = {k for k, n in current.items()
            if n > baseline_counts.get(k, 0)}
    return [f for f in findings if f.key in over or f.rule == "TL000"]
