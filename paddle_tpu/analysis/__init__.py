"""paddle_tpu.analysis — correctness tooling for a threaded, traced
framework.

Two passes, two failure families:

* `tracelint` — a pure-AST **trace-safety linter** for JAX-under-trace
  hazards (wall clocks, host RNG, concretization, closed-over mutation,
  swallowed exceptions, recompile traps). CLI: ``tools/tpu_lint.py``.
  Ratchet: ``.tpu_lint_baseline.json`` at the repo root freezes existing
  findings; new ones fail CI.
* `lockcheck` + `locks` — an opt-in (``PADDLE_TPU_LOCKCHECK=1``)
  **lock-order / race checker**: named lock constructors
  (``locks.new_lock("serving.pool")``), per-thread held-sets, a global
  acquisition-order graph with cycle detection, and
  blocked-while-holding probes at the framework's dispatch/IO points.
* `runtime_san` — an opt-in (``PADDLE_TPU_SAN=1``) **runtime
  sanitizer** (tpu-san): retrace sentinel, host-sync detector
  (``hot_region`` probes), donation guard, and non-finite guard, with
  site-keyed findings ratcheted via ``.tpu_san_baseline.json`` and
  ``tools/tpu_san.py``.
* `graphcheck` — an opt-in (``PADDLE_TPU_GRAPHCHECK=1``) **graph
  auditor**: statically walks the jaxpr/compiled HLO of every framework
  entrypoint (engine steps, AOT bucket executables, exported layer
  calls, decode steps) for unexpected collectives, accidental full
  replication, conv-region layout changes, host transfers, unaliased
  donation and a live-memory watermark; ratcheted via
  ``.graphcheck_baseline.json`` and ``tools/graph_audit.py``.
* `commcheck` — an opt-in (``PADDLE_TPU_COMMCHECK=1``)
  **collective-schedule auditor**: canonicalizes the ordered collective
  schedule (kind, mesh axes, operand shape/dtype, reduce op) of every
  entrypoint into a per-``site::program`` fingerprint, ratcheted via
  ``.commcheck_baseline.json`` and ``tools/comm_audit.py``; plus a
  cross-host runtime verifier over the coordination store that turns a
  schedule divergence into a typed
  ``CollectiveScheduleMismatchError(host, site, first_divergent_collective)``
  on every host instead of an unattributable hang.

See docs/static_analysis.md for the rule catalogue and workflows.
"""
from . import commcheck, graphcheck, lockcheck, locks, runtime_san  # noqa: F401

__all__ = ["commcheck", "graphcheck", "lockcheck", "locks", "runtime_san",
           "tracelint"]


def __getattr__(name):
    # tracelint (the full AST linter) loads lazily: every runtime import
    # of analysis.locks — including _atomic_io's, which promises a lean
    # import — must not pay for a module only tools/tpu_lint.py and the
    # lint tests need
    if name == "tracelint":
        # importlib, NOT `from . import ...`: the from-import form probes
        # this very __getattr__ mid-load and recurses
        import importlib
        return importlib.import_module(".tracelint", __name__)
    raise AttributeError(name)
