"""paddle_tpu.analysis.lockcheck — runtime lock-order / race checker.

The serving runtime, dynamic batcher, prefetch daemons and checkpoint
machinery are thread-heavy (worker pools, supervisors, condition
variables, timers). The classic failure modes there are silent until
production:

* **lock-order inversion** — thread 1 takes A then B, thread 2 takes B
  then A: a latent deadlock that only fires under the right interleaving;
* **blocking under a lock** — an XLA dispatch, `queue` wait or file write
  performed while holding a hot lock serializes the whole pool (and, if
  the blocked call needs the same lock to make progress, deadlocks);
* **long holds** — a convoy: everything else piles up on one mutex.

This module is the dynamic half of `paddle_tpu.analysis` (the static
half is tracelint). It is **opt-in**: set ``PADDLE_TPU_LOCKCHECK=1`` in
the environment (before the locks are constructed) or call ``enable()``
programmatically. When off, `analysis.locks.new_lock(name)` returns a
plain `threading.Lock` — zero overhead in production.

When on, every named lock is wrapped so the checker can record, per
thread, the set of locks currently held, and globally:

* the **acquisition-order graph**: an edge A→B each time B is acquired
  while A is held (first witness site + thread kept per edge). Cycles in
  this graph are potential deadlocks — reported by ``report()`` /
  ``assert_clean()`` even if the fatal interleaving never fired. Edges
  are per lock *name*, so two instances of the same name nesting (e.g.
  two request locks) form a self-loop cycle — also a real hazard unless
  instances are ordered.
* **held-across-blocking violations**: framework blocking points (XLA
  dispatch, compile-cache file IO, atomic writes) are annotated with
  ``locks.blocking_region("label")``; entering one while holding any
  checked lock is recorded.
* **held-across-wait**: `Condition.wait` releases its own lock but any
  OTHER checked lock still held during the wait is recorded the same way.
* **long holds** (warning only): a release more than
  ``PADDLE_TPU_LOCKCHECK_HOLD_S`` (default 0.5) seconds after acquire.

A same-thread re-acquire of a non-reentrant checked lock raises
immediately (the uninstrumented program would deadlock right there);
RLock reentrancy is understood and never reported.

Usage in tests / harnesses::

    from paddle_tpu.analysis import lockcheck
    lockcheck.enable()           # or PADDLE_TPU_LOCKCHECK=1 in the env
    ... construct pools, run the workload ...
    lockcheck.assert_clean()     # raises LockOrderError with the report

``report()`` returns the raw dict (cycles, violations, per-lock stats);
``reset()`` clears all recorded state (the enable flag stays).
"""
from __future__ import annotations

import os
import sys
import threading
import time

__all__ = [
    "enable", "disable", "enabled", "report", "reset", "assert_clean",
    "cycles", "violations", "LockOrderError", "Violation",
    "InstrumentedLock", "InstrumentedRLock", "InstrumentedCondition",
    "registry",
]

_ENV = "PADDLE_TPU_LOCKCHECK"
_ENV_HOLD = "PADDLE_TPU_LOCKCHECK_HOLD_S"

# case-insensitive off-values: an operator exporting FALSE/off/no to
# disable the checker must not silently get full instrumentation
_enabled = os.environ.get(_ENV, "").strip().lower() not in (
    "", "0", "false", "off", "no")


def enable():
    """Turn checking on for locks constructed AFTER this call."""
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def enabled():
    return _enabled


class LockOrderError(AssertionError):
    """Raised by assert_clean(); carries the full report dict."""

    def __init__(self, message, report):
        super().__init__(message)
        self.report = report


class Violation:
    __slots__ = ("kind", "message", "thread", "warning")

    def __init__(self, kind, message, thread, warning=False):
        self.kind = kind
        self.message = message
        self.thread = thread
        self.warning = warning

    def to_dict(self):
        return {"kind": self.kind, "message": self.message,
                "thread": self.thread, "warning": self.warning}

    def __repr__(self):
        tag = "warning" if self.warning else "violation"
        return f"[{tag}:{self.kind}] ({self.thread}) {self.message}"


def _caller_site():
    """file:line of the first frame outside this package (cheap: only
    walked when a NEW edge or a violation is recorded)."""
    f = sys._getframe(2)
    pkg = os.path.dirname(__file__)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(pkg):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


class _Registry:
    """Global recorder. Its own guard is a RAW threading.Lock — never an
    instrumented one (the recorder must not observe itself)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._live = {}        # lock -> (acquirer's held list, entry)
        self.edges = {}        # name -> {name: {"thread","site"}}
        self.violations = []
        self.acquire_counts = {}
        self.max_hold_s = {}
        self.hold_threshold_s = float(
            os.environ.get(_ENV_HOLD, "0.5") or "0.5")

    # -- per-thread held list --------------------------------------------
    def _held(self):
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_names(self):
        with self._mu:
            return [lock.name for lock, _ in self._held()]

    # -- events -----------------------------------------------------------
    # A held list is normally touched only by its own thread, but a
    # cross-thread Lock handoff release mutates the ACQUIRER's list, so
    # every read/write of any held list happens under _mu — otherwise a
    # handoff racing an acquire could snapshot a just-released lock into
    # an ordering edge (fabricating a cycle) or hide a genuine hold from
    # note_blocking.

    def on_acquire_attempt(self, lock, fail=True):
        """Called BEFORE blocking on a non-reentrant lock: a same-thread
        re-acquire would deadlock the uninstrumented program, so fail
        loudly here instead of hanging the test suite. With a finite
        timeout the call does eventually return False, so the pattern is
        recorded as a violation but the timeout semantics are kept."""
        with self._mu:
            mine = lock in [h for h, _ in self._held()]
        if mine:
            v = Violation(
                "recursive-acquire",
                f"thread re-acquired non-reentrant lock "
                f"'{lock.name}' it already holds "
                + ("(guaranteed deadlock)" if fail else
                   "(deadlock without the timeout)")
                + f" at {_caller_site()}",
                threading.current_thread().name)
            with self._mu:
                self.violations.append(v)
            if fail:
                raise RuntimeError("lockcheck: " + v.message)

    def on_acquired(self, lock):
        held = self._held()
        entry = (lock, time.monotonic())
        with self._mu:
            new_edges = [(h.name, lock.name) for h, _ in held
                         if h is not lock]
            held.append(entry)
            self._live[lock] = (held, entry)
            self.acquire_counts[lock.name] = \
                self.acquire_counts.get(lock.name, 0) + 1
            for a, b in new_edges:
                targets = self.edges.setdefault(a, {})
                if b not in targets:
                    targets[b] = {
                        "thread": threading.current_thread().name,
                        "site": _caller_site()}

    def on_release(self, lock, cross_thread=True):
        """Clear the recorded hold; True when one was actually cleared."""
        held = self._held()
        with self._mu:
            entry = None
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is lock:
                    entry = held[i]
                    break
            if entry is not None:
                owner_held = held
            elif cross_thread:
                # threading.Lock permits acquire in thread A / release
                # in thread B (handoff). The hold was recorded in the
                # ACQUIRING thread's list — clear it there, or A carries
                # a phantom hold that later fabricates recursive-acquire
                # and held-across-blocking reports
                rec = self._live.get(lock)
                if rec is None:
                    # never saw the acquire (e.g. enable() raced
                    # construction) — ignore rather than crash the host
                    return False
                owner_held, entry = rec
            else:
                return False
            try:
                owner_held.remove(entry)
            except ValueError:
                return False           # lost a race with another release
            self._live.pop(lock, None)
            dur = time.monotonic() - entry[1]
            if dur > self.max_hold_s.get(lock.name, 0.0):
                self.max_hold_s[lock.name] = dur
            if dur > self.hold_threshold_s:
                self.violations.append(Violation(
                    "long-hold",
                    f"'{lock.name}' held for {dur * 1e3:.0f}ms "
                    f"(threshold "
                    f"{self.hold_threshold_s * 1e3:.0f}ms), "
                    f"released at {_caller_site()}",
                    threading.current_thread().name,
                    warning=True))
        return True

    def note_blocking(self, label):
        held = self.held_names()
        if held:
            with self._mu:
                self.violations.append(Violation(
                    "held-across-blocking",
                    f"blocking region '{label}' entered while holding "
                    f"{held} at {_caller_site()}",
                    threading.current_thread().name))

    def note_wait(self, cond_lock):
        others = [n for n in self.held_names() if n != cond_lock.name]
        if others:
            with self._mu:
                self.violations.append(Violation(
                    "held-across-wait",
                    f"Condition('{cond_lock.name}').wait() while still "
                    f"holding {others} at {_caller_site()}",
                    threading.current_thread().name))

    # -- analysis ---------------------------------------------------------
    def cycles(self):
        """Elementary cycles in the name-level acquisition-order graph
        (iterative DFS; the graph is tiny — tens of names)."""
        with self._mu:
            graph = {a: sorted(bs) for a, bs in self.edges.items()}
        found, seen = [], set()
        for start in sorted(graph):
            stack = [(start, [start])]
            while stack:
                node, path = stack.pop()
                for nxt in graph.get(node, ()):
                    if nxt == start:
                        # canonical ROTATION of the ordered path — a node
                        # set would merge A->B->C->A with A->C->B->A,
                        # which are two distinct ordering hazards
                        i = path.index(min(path))
                        canon = tuple(path[i:] + path[:i])
                        if canon not in seen:
                            seen.add(canon)
                            found.append(path + [start])
                    elif nxt not in path and nxt > start:
                        # only explore nodes > start: each cycle is
                        # discovered once, from its smallest member
                        stack.append((nxt, path + [nxt]))
        return found

    def report(self):
        with self._mu:
            vio = [v.to_dict() for v in self.violations]
            edges = {a: {b: dict(w) for b, w in bs.items()}
                     for a, bs in self.edges.items()}
            stats = {n: {"acquires": self.acquire_counts.get(n, 0),
                         "max_hold_ms": round(
                             self.max_hold_s.get(n, 0.0) * 1e3, 3)}
                     for n in sorted(self.acquire_counts)}
        return {"cycles": self.cycles(), "violations": vio,
                "edges": edges, "locks": stats}

    def reset(self):
        with self._mu:
            self._live = {}
            self.edges = {}
            self.violations = []
            self.acquire_counts = {}
            self.max_hold_s = {}


_registry = _Registry()


def registry():
    return _registry


# --------------------------------------------------------------------------
# instrumented primitives (constructed via analysis.locks.new_* when the
# checker is enabled)
# --------------------------------------------------------------------------

class InstrumentedLock:
    """threading.Lock wrapper reporting to the global registry."""

    _reentrant = False

    def __init__(self, name, reg=None):
        self.name = name
        self._reg = reg or _registry
        self._inner = threading.Lock()

    def acquire(self, blocking=True, timeout=-1):
        if blocking:
            self._reg.on_acquire_attempt(self, fail=timeout == -1)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._reg.on_acquired(self)
        return ok

    def release(self):
        self._reg.on_release(self)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<InstrumentedLock '{self.name}'>"


class InstrumentedRLock:
    """threading.RLock wrapper: only the OUTERMOST acquire/release pair
    is reported, so reentrancy never shows up as ordering or recursion."""

    _reentrant = True

    def __init__(self, name, reg=None):
        self.name = name
        self._reg = reg or _registry
        self._inner = threading.RLock()
        self._owner = None          # ident; only mutated by the owner
        self._depth = 0

    def acquire(self, blocking=True, timeout=-1):
        me = threading.get_ident()
        if self._owner == me:        # reentrant fast path, we own it
            self._inner.acquire()
            self._depth += 1
            return True
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._owner = me
            self._depth = 1
            self._reg.on_acquired(self)
        return ok

    def release(self):
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._depth -= 1
        if self._depth == 0:
            self._owner = None
            self._reg.on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<InstrumentedRLock '{self.name}'>"


class InstrumentedCondition:
    """Condition over an InstrumentedLock. The real threading.Condition
    runs on the RAW inner lock (its `_is_owned` probe would corrupt the
    wrapper's bookkeeping), while acquire/release/wait go through the
    wrapper so held-sets stay truthful across waits."""

    def __init__(self, lock):
        # plain Lock only: RLock wait() semantics (full release of a
        # nested hold) can't be mirrored in the wrapper's bookkeeping
        if not isinstance(lock, InstrumentedLock):
            raise TypeError("InstrumentedCondition needs an "
                            f"InstrumentedLock, got {type(lock).__name__}")
        self.lock = lock
        self._reg = lock._reg
        self._cond = threading.Condition(lock._inner)

    def acquire(self, *a, **kw):
        return self.lock.acquire(*a, **kw)

    def release(self):
        self.lock.release()

    def __enter__(self):
        self.lock.acquire()
        return self

    def __exit__(self, *exc):
        self.lock.release()
        return False

    def wait(self, timeout=None):
        self._reg.note_wait(self.lock)
        # the wait releases (and on wake re-acquires) the inner lock:
        # mirror that in the held-set so hold-times and ordering edges
        # seen by OTHER acquisitions during the wait stay correct.
        # cross_thread=False: Condition.wait only ever releases the
        # CALLER's hold — and only restore what was actually cleared,
        # else waiting without the lock (inner wait raises) would plant
        # a phantom hold that poisons every later report on this thread
        released = self._reg.on_release(self.lock, cross_thread=False)
        try:
            return self._cond.wait(timeout)
        finally:
            if released:
                self._reg.on_acquired(self.lock)

    def wait_for(self, predicate, timeout=None):
        end = None if timeout is None else time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None if end is None else end - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n=1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __repr__(self):
        return f"<InstrumentedCondition over '{self.lock.name}'>"


# --------------------------------------------------------------------------
# module-level conveniences
# --------------------------------------------------------------------------

def report():
    return _registry.report()


def cycles():
    return _registry.cycles()


def violations(include_warnings=False):
    with _registry._mu:
        vs = list(_registry.violations)
    if not include_warnings:
        vs = [v for v in vs if not v.warning]
    return vs


def reset():
    _registry.reset()


def assert_clean(allow_warnings=True):
    """Raise LockOrderError if any cycle or (non-warning) violation was
    recorded. The exception message embeds the findings; `.report` has
    the full dict."""
    rep = report()
    problems = []
    for cyc in rep["cycles"]:
        problems.append("acquisition-order cycle: " + " -> ".join(cyc))
    for v in rep["violations"]:
        if v["warning"] and allow_warnings:
            continue
        problems.append(f"{v['kind']} ({v['thread']}): {v['message']}")
    if problems:
        raise LockOrderError(
            "lockcheck found {} problem(s):\n  {}".format(
                len(problems), "\n  ".join(problems)), rep)
    return rep
