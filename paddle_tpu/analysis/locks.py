"""paddle_tpu.analysis.locks — named lock constructors + blocking markers.

The framework's thread-synchronization points are created through these
constructors instead of bare ``threading.Lock()`` so that

* every lock has a stable human-readable name (``"serving.pool"``,
  ``"aot.compile_cache"`` ...) — lockcheck reports and acquisition-order
  graphs speak in those names instead of ``<locked _thread.lock object
  at 0x...>``;
* when the checker is off (the default), they return the PLAIN
  ``threading`` primitive — zero overhead, byte-identical behavior;
* when ``PADDLE_TPU_LOCKCHECK=1`` (or ``lockcheck.enable()`` ran before
  construction), they return the instrumented wrappers from
  `paddle_tpu.analysis.lockcheck`.

Blocking points (XLA dispatch, compile-cache file IO, atomic writes,
unbounded queue waits) are annotated in the framework with::

    with locks.blocking_region("serving.execute"):
        result = executable(...)

which is a no-op singleton when the checker is off and a
held-locks-at-blocking-call probe when it is on.

Names are free-form dotted strings; instances may share a name (each
request's result lock is ``"serving.request"``) — ordering analysis is
per NAME, which also catches two same-class instances nesting.
"""
from __future__ import annotations

import threading

from . import lockcheck

__all__ = ["new_lock", "new_rlock", "new_condition", "blocking_region",
           "is_checked"]


def new_lock(name):
    """A mutex named `name`: plain threading.Lock when the checker is
    off, an InstrumentedLock when it is on."""
    if lockcheck.enabled():
        return lockcheck.InstrumentedLock(name)
    return threading.Lock()


def new_rlock(name):
    if lockcheck.enabled():
        return lockcheck.InstrumentedRLock(name)
    return threading.RLock()


def new_condition(name, lock=None):
    """A condition variable. `lock` may be a lock previously returned by
    `new_lock` (shared lock/cv idiom); when omitted a fresh lock named
    `name` is created."""
    if lock is None:
        lock = new_lock(name)
    if isinstance(lock, lockcheck.InstrumentedLock):
        return lockcheck.InstrumentedCondition(lock)
    if isinstance(lock, lockcheck.InstrumentedRLock):
        raise TypeError("condition over a checked RLock is unsupported; "
                        "use new_lock() for the condition's mutex")
    return threading.Condition(lock)


def is_checked(lock):
    return isinstance(lock, (lockcheck.InstrumentedLock,
                             lockcheck.InstrumentedRLock))


class _NullRegion:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullRegion()


class _CheckedRegion:
    __slots__ = ("label",)

    def __init__(self, label):
        self.label = label

    def __enter__(self):
        lockcheck.registry().note_blocking(self.label)
        return self

    def __exit__(self, *exc):
        return False


def blocking_region(label):
    """Mark a blocking call site (dispatch / file IO / queue wait).
    Entering it while holding any checked lock records a
    held-across-blocking violation. Free when the checker is off."""
    if lockcheck.enabled():
        return _CheckedRegion(label)
    return _NULL
