"""paddle_tpu.analysis.runtime_san — tpu-san, the runtime sanitizer.

The static half of `paddle_tpu.analysis` (tracelint) catches hazards the
AST can prove; the failure modes that actually burn a JAX/TPU stack in
production are invisible to it because they only exist at runtime:

* **silent retraces** — a jit entrypoint recompiling after warmup
  (shape/dtype/weak_type drift, an unstable cache key) tanks steps/sec
  with no error anywhere;
* **host syncs inside a dispatch region** — an `np.asarray` / `.item()`
  / `jax.device_get` on a device array in the middle of the serving or
  training hot path serializes the pipeline;
* **use-after-donate** — reading a buffer the engine donated to XLA
  raises a cryptic "Array has been deleted" far from the donation site;
* **non-finite values** — a NaN/Inf born in step 3 of a 30-step
  `lax.scan` surfaces as a garbage loss with no blame.

This module is the *dynamic sanitizer* for those four: opt-in via
``PADDLE_TPU_SAN=1`` (or :func:`enable`), zero overhead when off —
every probe in the framework reduces to one module-flag check, exactly
like lockcheck's constructors. When on:

* framework compile/trace points call :func:`note_trace`; an identical
  signature compiled twice, or any new signature after the entrypoint
  was marked warm (:func:`mark_warm`), records a **retrace** finding
  with the shape/dtype/weak_type delta that caused it;
* the hot paths are wrapped in :func:`hot_region` probes (the sibling
  of lockcheck's ``blocking_region``); `numpy.asarray`/`numpy.array`
  and `jax.device_get` are patched so a device-array→host conversion
  mid-region records a **host-sync** finding with the offending stack
  site.  Sanctioned readbacks (a Predictor fetching its outputs, the
  decode engine streaming a token) sit in :func:`allow_host_sync`
  escapes — the runtime analog of a lint suppression;
* the engine reports its donated carry buffers via
  :func:`note_donation`; any later use (framework choke points call
  :func:`check_use`; the numpy/device_get patches check too) raises a
  typed :class:`DonatedBufferError` naming the donation site, instead
  of jax's anonymous deletion error;
* after each dispatch the engine (and the decode engine's KV pool)
  sweeps for NaN/Inf via :func:`check_finite`, which raises
  :class:`NonFiniteError` blaming the FIRST offending leaf by path
  (``param/linear.weight``, ``kv_pool/layer0/k``, ...). Disable just
  this detector with ``PADDLE_TPU_SAN_NONFINITE=0`` (the sweep costs a
  device reduction + readback per leaf per dispatch).

Findings are keyed **site-wise and line-number-free**
(``<site>::<detector>``, e.g. ``engine.dispatch::host-sync``) so they
ratchet through a checked-in baseline exactly like tracelint:
``.tpu_san_baseline.json`` at the repo root, driven by
``tools/tpu_san.py`` (exit 0 clean / 1 new findings / 2 usage error).
Counts also export as the ``san`` collector on the obs registry
(``san_findings``, ``san_retrace``, ... in the Prometheus exposition).

Dogfood: ``tools/serving_fault_injector.py`` runs every fault phase
with the sanitizer live and asserts ZERO findings — the serving /
batching / decode / router stacks are retrace-free and sync-free even
while members crash, wedge and hot-swap.
"""
from __future__ import annotations

import os
import sys
import threading
import weakref

__all__ = [
    "enable", "disable", "enabled", "reset", "report", "findings",
    "counts_by_key", "assert_clean", "mark_warm",
    "note_trace", "hot_region", "allow_host_sync", "in_hot_region",
    "note_donation", "check_use", "check_finite", "nonfinite_enabled",
    "aval_signature", "sharding_signature", "Finding", "SanError",
    "DonatedBufferError",
    "NonFiniteError", "load_baseline", "write_baseline", "new_counts",
    "OBS_COLLECTOR",
]

_ENV = "PADDLE_TPU_SAN"
_ENV_NONFINITE = "PADDLE_TPU_SAN_NONFINITE"

DETECTORS = ("retrace", "host-sync", "donation", "non-finite")

#: obs-registry collector name (docs/observability.md)
OBS_COLLECTOR = "san"

#: per-key cap on stored Finding exemplars (counts stay exact)
_MAX_SAMPLES = 5
#: donation-table size bound (dead weakrefs pruned past this)
_MAX_DONATIONS = 4096
#: retrace-entrypoint table bound: fingerprint-less compiles and churned
#: layer instances each add an entry; a long-lived sanitized process must
#: not grow without bound, so the OLDEST entries are dropped past this
#: (losing their warm state — bounded memory beats perfect recall)
_MAX_ENTRYPOINTS = 4096

_off_values = ("", "0", "false", "off", "no")


def _env_on(name, default=""):
    return os.environ.get(name, default).strip().lower() not in _off_values


# case-insensitive off-values, mirroring lockcheck: exporting
# PADDLE_TPU_SAN=FALSE/off/no must not silently enable full patching
_enabled = _env_on(_ENV)


class SanError(RuntimeError):
    """Base class of the sanitizer's typed errors."""


class DonatedBufferError(SanError):
    """A buffer donated to XLA was used again. The message names the
    donation site (e.g. ``engine.dispatch step 12``) instead of jax's
    anonymous "Array has been deleted"."""


class NonFiniteError(SanError):
    """A NaN/Inf appeared after a dispatch. The message blames the first
    offending leaf by path."""

    def __init__(self, message, site="", path=""):
        super().__init__(message)
        self.site = site
        self.path = path


class Finding:
    """One sanitizer hit. `key` is the baseline identity — site and
    detector only, no line numbers, no instance ids — so the ratchet
    never churns when code moves."""

    __slots__ = ("detector", "site", "message")

    def __init__(self, detector, site, message):
        self.detector = detector
        self.site = site
        self.message = message

    @property
    def key(self):
        return f"{self.site}::{self.detector}"

    def to_dict(self):
        return {"detector": self.detector, "site": self.site,
                "message": self.message}

    def __repr__(self):
        return f"[{self.detector}] {self.site}: {self.message}"


def _caller_site():
    """``file.py:line`` of the nearest frame outside this package plus,
    when different, the nearest frame outside paddle_tpu entirely —
    blame lands on the framework call AND the user code driving it."""
    pkg = os.path.dirname(__file__)
    root = os.path.dirname(os.path.dirname(pkg))   # repo root-ish
    tree = os.path.dirname(pkg)                    # paddle_tpu/
    near = far = None
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(pkg) and "numpy" not in fn:
            if near is None:
                near = (fn, f.f_lineno)
            if not fn.startswith(tree):
                far = (fn, f.f_lineno)
                break
        f = f.f_back
    if near is None:
        return "<unknown>"

    def fmt(site):
        fn, ln = site
        try:
            rel = os.path.relpath(fn, root)
        except ValueError:
            rel = fn
        if rel.startswith(".."):
            rel = os.path.basename(fn)
        return f"{rel}:{ln}"

    if far is not None and far != near:
        return f"{fmt(near)} (from {fmt(far)})"
    return fmt(near)


def _flatten_sig(sig, out):
    if isinstance(sig, (tuple, list)):
        for s in sig:
            _flatten_sig(s, out)
    else:
        out.append(sig)
    return out


_SHARDING_TAG = "sharding:"


def _describe_delta(old_sig, new_sig):
    """Human-readable diff between two trace signatures (the
    shape/dtype/weak_type drift that caused a retrace). Leaves carrying
    the ``sharding:`` tag (see :func:`sharding_signature`) are rendered
    as a placement change — a mesh/spec swap that forces a recompile is
    named as such instead of surfacing as an anonymous leaf diff."""
    a = _flatten_sig(old_sig, [])
    b = _flatten_sig(new_sig, [])
    if len(a) != len(b):
        return (f"signature arity/structure changed "
                f"({len(a)} -> {len(b)} leaves)")
    diffs, shard = [], []
    for i, (x, y) in enumerate(zip(a, b)):
        if x == y:
            continue
        if isinstance(x, str) and isinstance(y, str) and \
                x.startswith(_SHARDING_TAG) and y.startswith(_SHARDING_TAG):
            shard.append(f"{x[len(_SHARDING_TAG):]} -> "
                         f"{y[len(_SHARDING_TAG):]}")
        else:
            diffs.append(f"leaf {i}: {x!r} -> {y!r}")
    if not diffs and not shard:
        return "identical signature"
    parts = []
    if shard:
        parts.append("sharding signature changed (mesh/spec): "
                     + "; ".join(shard))
    if diffs:
        shown = "; ".join(diffs[:4])
        if len(diffs) > 4:
            shown += f"; ... {len(diffs) - 4} more"
        parts.append(shown)
    return " | ".join(parts)


class _Tls(threading.local):
    def __init__(self):
        self.regions = []      # hot-region label stack
        self.allow = 0         # allow_host_sync nesting depth


class _Registry:
    """Global recorder. Guarded by a RAW threading.Lock on purpose (the
    recorder must not observe itself through lockcheck — same rule as
    lockcheck's own registry)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = _Tls()
        self._counts = {}        # finding key -> exact count
        self._samples = {}       # finding key -> [Finding] (capped)
        self._entries = {}       # (site, entry_key) -> {"sigs", "warm", "last"}
        self._donated = {}       # id(arr) -> (weakref, site, tag)
        self.counters = {"traces": 0, "hot_regions": 0, "donations": 0,
                         "finite_checks": 0, "use_checks": 0}

    # -- findings ---------------------------------------------------------
    def record(self, detector, site, message):
        f = Finding(detector, site, message)
        with self._mu:
            self._counts[f.key] = self._counts.get(f.key, 0) + 1
            samples = self._samples.setdefault(f.key, [])
            if len(samples) < _MAX_SAMPLES:
                samples.append(f)
        return f

    def findings(self):
        with self._mu:
            return [f for ss in self._samples.values() for f in ss]

    def counts_by_key(self):
        with self._mu:
            return dict(self._counts)

    # -- retrace sentinel --------------------------------------------------
    def note_trace(self, site, entry_key, signature, per_call=False):
        """Record one trace of `signature` at jit entrypoint
        (site, entry_key).  per_call=True marks a call-site probe on a
        caching jit object: a repeated signature there is a cache HIT,
        not a retrace. At explicit compile sites (per_call=False) a
        repeated signature means the compile cache failed — always a
        finding. A NEW signature is a finding only once the entrypoint
        is warm (mark_warm)."""
        ek = (site, entry_key)
        with self._mu:
            ent = self._entries.pop(ek, None)
            if ent is None:
                if len(self._entries) >= _MAX_ENTRYPOINTS:
                    # evict the least-recently-TOUCHED entry (the
                    # pop/re-insert above keeps dict order ≈ recency):
                    # plain insertion-order FIFO would evict the busy
                    # process-lifetime entrypoints first and silently
                    # disarm their warm state
                    self._entries.pop(next(iter(self._entries)))
                ent = {"sigs": set(), "warm": False, "last": None}
            self._entries[ek] = ent
            dup = signature in ent["sigs"]
            warm = ent["warm"]
            last = ent["last"]
            ent["sigs"].add(signature)
            ent["last"] = signature
            if not (dup and per_call):
                self.counters["traces"] += 1
        if dup:
            if per_call:
                return
            self.record(
                "retrace", site,
                f"identical signature compiled twice (the compile cache "
                f"should have hit) at {_caller_site()}")
        elif warm:
            delta = _describe_delta(last, signature) if last is not None \
                else "first signature after warm mark"
            self.record(
                "retrace", site,
                f"retrace after warmup — {delta} — at {_caller_site()}")

    def mark_warm(self, site=None):
        """Declare warmup over: every signature the matching entrypoints
        later trace is a retrace finding. site=None marks ALL entrypoints
        seen so far (entrypoints created later start cold — a freshly
        loaded model legitimately compiles)."""
        with self._mu:
            for (s, _k), ent in self._entries.items():
                if site is None or s == site:
                    ent["warm"] = True

    # -- host-sync detector ------------------------------------------------
    def region_enter(self, label):
        self._tls.regions.append(label)
        with self._mu:
            self.counters["hot_regions"] += 1

    def region_exit(self):
        self._tls.regions.pop()

    def current_region(self):
        tls = self._tls
        if tls.regions and not tls.allow:
            return tls.regions[-1]
        return None

    def note_sync(self, what):
        region = self.current_region()
        if region is None:
            return
        self.record(
            "host-sync", region,
            f"{what} on a device array inside hot region '{region}' "
            f"at {_caller_site()}")

    # -- donation guard ----------------------------------------------------
    def note_donation(self, site, leaves, tag=None):
        with self._mu:
            self.counters["donations"] += 1
            if len(self._donated) > _MAX_DONATIONS:
                self._donated = {i: rec for i, rec in self._donated.items()
                                 if rec[0]() is not None}
            for leaf in leaves:
                try:
                    ref = weakref.ref(leaf)
                except TypeError:
                    continue
                self._donated[id(leaf)] = (ref, site, tag)

    def donation_site(self, value):
        with self._mu:
            rec = self._donated.get(id(value))
        if rec is not None and rec[0]() is value:
            return rec[1], rec[2]
        return None, None

    def reset(self):
        with self._mu:
            self._counts = {}
            self._samples = {}
            self._entries = {}
            self._donated = {}
            self.counters = {k: 0 for k in self.counters}

    def report(self):
        with self._mu:
            return {
                "counts": dict(self._counts),
                "findings": [f.to_dict() for ss in self._samples.values()
                             for f in ss],
                "by_detector": {
                    d: sum(n for k, n in self._counts.items()
                           if k.endswith("::" + d)) for d in DETECTORS},
                "counters": dict(self.counters),
                "entrypoints": len(self._entries),
            }


_registry = _Registry()


def registry():
    return _registry


# ---------------------------------------------------------------------------
# enable / disable + interposers
# ---------------------------------------------------------------------------

_np_orig = {}
_jax_orig = {}


def _device_array(x):
    """The concrete jax array behind `x`, or None. Tracers are excluded:
    a trace-time conversion raises jax's own (better) error and is
    tracelint's territory, not a runtime host sync."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        if isinstance(x, jax.core.Tracer):
            return None
        if isinstance(x, jax.Array):
            return x
    except Exception:  # tpu-lint: disable=TL007 — exotic array-likes may
        return None    # raise from isinstance; never break the host call
    return None


def _donation_of(arr):
    """(site, tag) when `arr` is known-donated. The registry's own
    record comes first: the CPU backend does not implement donation, so
    a donated buffer stays physically readable there — but the same
    program deletes it on TPU, and tier-1 must catch that bug on CPU."""
    site, tag = _registry.donation_site(arr)
    if site is not None:
        return site, tag
    if arr.is_deleted():
        return "<unknown-donation>", None
    return None, None


def _precheck(x, what):
    """Shared body of the patched converters: use-after-donate first
    (a typed error beats jax's anonymous one anywhere, hot region or
    not), then the mid-region sync probe."""
    arr = _device_array(x)
    if arr is None:
        return
    site, tag = _donation_of(arr)
    if site is not None:
        where = f"donated at {site}" + (f" ({tag})" if tag else "")
        _registry.record("donation", site,
                         f"{what} on a donated buffer ({where}) "
                         f"at {_caller_site()}")
        raise DonatedBufferError(
            f"use-after-donate: {what} on a buffer {where}; donated "
            f"buffers are invalidated in place — read the engine's live "
            f"state (param_vals / Parameter) instead. At {_caller_site()}")
    _registry.note_sync(what)


def _install():
    import numpy as np

    if "asarray" not in _np_orig:
        _np_orig["asarray"] = np.asarray
        _np_orig["array"] = np.array

        def asarray(a, *args, **kw):
            _precheck(a, "np.asarray()")
            return _np_orig["asarray"](a, *args, **kw)

        def array(a, *args, **kw):
            _precheck(a, "np.array()")
            return _np_orig["array"](a, *args, **kw)

        np.asarray = asarray
        np.array = array
    jax = sys.modules.get("jax")
    if jax is not None and "device_get" not in _jax_orig:
        _jax_orig["device_get"] = jax.device_get

        def device_get(x):
            for leaf in _iter_leaves(x):
                _precheck(leaf, "jax.device_get()")
            return _jax_orig["device_get"](x)

        jax.device_get = device_get
    # san.* counters on the obs registry (weak-collector semantics don't
    # apply to a module function; unregistered again on disable())
    try:
        from ..obs.metrics import registry as _obs
        _obs().register_collector(OBS_COLLECTOR, _obs_collect)
    except Exception:  # tpu-lint: disable=TL007 — obs is optional here:
        pass           # the sanitizer must work without the registry


def _uninstall():
    import numpy as np

    if "asarray" in _np_orig:
        np.asarray = _np_orig.pop("asarray")
        np.array = _np_orig.pop("array")
    jax = sys.modules.get("jax")
    if jax is not None and "device_get" in _jax_orig:
        jax.device_get = _jax_orig.pop("device_get")
    try:
        from ..obs.metrics import registry as _obs
        _obs().unregister_collector(OBS_COLLECTOR)
    except Exception:  # tpu-lint: disable=TL007 — symmetric with _install
        pass


def _iter_leaves(x):
    if isinstance(x, (list, tuple)):
        for e in x:
            yield from _iter_leaves(e)
    elif isinstance(x, dict):
        for e in x.values():
            yield from _iter_leaves(e)
    else:
        yield x


def _obs_collect():
    rep = _registry.report()
    out = {"enabled": 1, "findings": sum(rep["counts"].values()),
           "entrypoints": rep["entrypoints"]}
    out.update({d.replace("-", "_"): n
                for d, n in rep["by_detector"].items()})
    out.update(rep["counters"])
    return out


def enable():
    """Turn the sanitizer on: installs the numpy/jax interposers and the
    obs collector. Probes constructed before this call work immediately
    (they check the module flag per entry, unlike lockcheck's
    construction-time decision)."""
    global _enabled
    _enabled = True
    _install()


def disable():
    global _enabled
    _enabled = False
    _uninstall()


def enabled():
    return _enabled


def nonfinite_enabled():
    return _enabled and _env_on(_ENV_NONFINITE, default="1")


def reset():
    """Clear all recorded state (the enable flag stays)."""
    _registry.reset()


# install at import when the env asks for it (the interposers only need
# numpy; jax is patched lazily if/when it is imported — see hot_region)
if _enabled:
    _install()


# ---------------------------------------------------------------------------
# probes (all free when the sanitizer is off)
# ---------------------------------------------------------------------------

class _NullRegion:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullRegion()


class _HotRegion:
    __slots__ = ("label",)

    def __init__(self, label):
        self.label = label

    def __enter__(self):
        _registry.region_enter(self.label)
        return self

    def __exit__(self, *exc):
        _registry.region_exit()
        return False


class _AllowSync:
    __slots__ = ()

    def __enter__(self):
        _registry._tls.allow += 1
        return self

    def __exit__(self, *exc):
        _registry._tls.allow -= 1
        return False


def hot_region(label):
    """Mark a dispatch hot path (sibling of lockcheck's
    ``blocking_region``): any device-array→host conversion by this
    thread inside the region records a host-sync finding. Free when the
    sanitizer is off."""
    if not _enabled:
        return _NULL
    if "device_get" not in _jax_orig and "jax" in sys.modules:
        _install()       # jax imported after enable(): patch it now
    return _HotRegion(label)


def allow_host_sync(reason=""):
    """Sanction a deliberate readback inside a hot region (result fetch,
    token streaming) — the runtime analog of a lint suppression."""
    if not _enabled:
        return _NULL
    return _AllowSync()


def in_hot_region():
    return _enabled and _registry.current_region() is not None


def note_trace(site, entry_key, signature, per_call=False):
    if _enabled:
        _registry.note_trace(site, entry_key, signature, per_call=per_call)


def mark_warm(site=None):
    if _enabled:
        _registry.mark_warm(site)


def aval_signature(values):
    """Hashable (shape, dtype, weak_type) signature of a pytree of
    arrays / ShapeDtypeStructs — the retrace sentinel's cache-key
    analog."""
    def leaf(v):
        # one STRING per array: the retrace delta then diffs whole
        # avals ("(2, 8)/float32 -> (3, 8)/float32"), not digits
        shape = tuple(getattr(v, "shape", ()))
        dtype = str(getattr(v, "dtype", type(v).__name__))
        weak = "/weak" if getattr(v, "weak_type", False) else ""
        return f"{shape}/{dtype}{weak}"

    def walk(v):
        if isinstance(v, dict):
            return tuple((k, walk(v[k])) for k in sorted(v))
        if isinstance(v, (list, tuple)):
            return tuple(walk(e) for e in v)
        return leaf(v)

    return walk(values)


def sharding_signature(mesh, specs=None):
    """One tagged, hashable signature leaf describing a placement (mesh
    axis sizes + optional per-name PartitionSpecs). Ride it alongside
    :func:`aval_signature` in a ``note_trace`` signature: when the only
    delta after warmup is this leaf, the retrace finding is blamed as a
    *sharding signature change* (a mesh swap, a ``shard_()`` re-place, a
    rule-table edit) instead of a generic leaf diff."""
    if mesh is None:
        base = "none"
    else:
        try:
            base = "mesh(" + ",".join(
                f"{a}={int(s)}" for a, s in dict(mesh.shape).items()) + ")"
        except Exception:  # tpu-lint: disable=TL007 — mesh-likes vary;
            base = "mesh(?)"   # a best-effort label beats a crash
    if specs:
        try:
            items = sorted(specs.items()) if isinstance(specs, dict) \
                else list(enumerate(specs))
            body = ";".join(
                f"{k}={tuple(v) if v is not None else ()}"
                for k, v in items)
        except Exception:  # tpu-lint: disable=TL007 — same best-effort
            body = "?"
        if len(body) > 256:
            import hashlib
            body = hashlib.sha1(body.encode()).hexdigest()[:16]
        base += "|" + body
    return _SHARDING_TAG + base


def note_donation(site, tree, tag=None):
    """Record that every array leaf of `tree` was just donated to a
    dispatch at `site` (called AFTER the dispatch; the leaves are the
    pre-dispatch buffers). `tag` rides into the blame message."""
    if not _enabled:
        return
    leaves = [v for v in _iter_leaves(tree) if _device_array(v) is not None]
    _registry.note_donation(site, leaves, tag=tag)


def check_use(value, context=""):
    """Raise DonatedBufferError (naming the donation site) if `value` is
    a donated/deleted device array. Framework choke points (batch
    placement, external-write adoption) call this so the error surfaces
    where the stale buffer ENTERS the engine, not inside XLA."""
    if not _enabled:
        return value
    with _registry._mu:       # same discipline as every other counter
        _registry.counters["use_checks"] += 1
    arr = _device_array(value)
    if arr is not None:
        site, tag = _donation_of(arr)
        if site is not None:
            where = f"donated at {site}" + (f" ({tag})" if tag else "")
            _registry.record("donation", site,
                             f"{context or 'use'} of a donated buffer "
                             f"({where}) at {_caller_site()}")
            raise DonatedBufferError(
                f"use-after-donate{': ' + context if context else ''} — "
                f"buffer was {where}. Donated buffers are invalidated in "
                f"place; re-read live engine state instead.")
    return value


def check_finite(site, named_leaves):
    """NaN/Inf sweep over ``(path, value)`` pairs; raises NonFiniteError
    blaming the FIRST offending leaf (and records a finding keyed to
    `site`). Non-float leaves are skipped. No-op unless the sanitizer
    AND its non-finite detector are on."""
    if not nonfinite_enabled():
        return
    import numpy as np
    jnp = None
    _registry.counters["finite_checks"] += 1
    with allow_host_sync("san.finite_sweep"):
        for path, value in named_leaves:
            # device-array FIRST, Tensor-unwrap second: jax's ArrayImpl
            # has its own private `_value` (cached numpy) — a blind
            # getattr would silently pull every device array to the
            # host AND route bf16 through numpy's dtype lattice
            arr = _device_array(value)
            v = value if arr is not None else \
                getattr(value, "_value", value)    # Tensor -> array
            if arr is None:
                arr = _device_array(v)
            dt = getattr(v, "dtype", None)
            if dt is None:
                continue
            if arr is not None:
                if jnp is None:
                    import jax.numpy as jnp
                # jnp.issubdtype, NOT np.issubdtype: numpy does not put
                # bfloat16 (or any ml_dtypes float) under np.floating,
                # which would silently skip bf16 params and KV pools —
                # the very tensors this sweep exists for
                if not jnp.issubdtype(dt, jnp.floating):
                    continue
                ok = bool(jnp.isfinite(v).all())
            else:
                if not np.issubdtype(np.dtype(dt), np.floating):
                    continue
                ok = bool(np.isfinite(np.asarray(v)).all())
            if not ok:
                _registry.record(
                    "non-finite", site,
                    f"non-finite value in leaf '{path}' after dispatch "
                    f"at {_caller_site()}")
                raise NonFiniteError(
                    f"non-finite value detected at '{site}': first "
                    f"offending leaf is '{path}' "
                    f"(shape {tuple(getattr(v, 'shape', ()))}). The "
                    f"dispatch that produced it is the one blamed by "
                    f"this site; earlier steps were finite.",
                    site=site, path=path)


# ---------------------------------------------------------------------------
# module-level report / ratchet surface
# ---------------------------------------------------------------------------

def findings():
    return _registry.findings()


def counts_by_key():
    return _registry.counts_by_key()


def report():
    return _registry.report()


def assert_clean():
    """Raise SanError if any finding was recorded (message embeds the
    exemplars). The fault injector's final verdict."""
    rep = _registry.report()
    total = sum(rep["counts"].values())
    if total:
        lines = [f"  {f['site']} [{f['detector']}]: {f['message']}"
                 for f in rep["findings"]]
        raise SanError(
            f"tpu-san found {total} finding(s):\n" + "\n".join(lines))
    return rep


def load_baseline(path):
    import json

    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "counts" not in data:
        raise ValueError(f"{path}: not a tpu-san baseline "
                         "(missing 'counts')")
    return data["counts"]


def write_baseline(path, counts):
    """Deterministic (sorted-keys, newline-terminated) baseline dump —
    same shape as the tracelint ratchet so the two review identically."""
    import json

    data = {"version": 1, "tool": "tpu_san", "counts": dict(counts)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def new_counts(counts, baseline_counts):
    """{key: (count, baselined)} for keys whose count exceeds the
    baselined count — the ratchet's failing set."""
    return {k: (n, baseline_counts.get(k, 0))
            for k, n in sorted(counts.items())
            if n > baseline_counts.get(k, 0)}
