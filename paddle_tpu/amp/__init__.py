"""Auto mixed precision (reference: python/paddle/amp/ — auto_cast
auto_cast.py:703, decorate :787, GradScaler grad_scaler.py).

TPU-native: bf16 is the native mixed-precision dtype (no loss scaling
needed); fp16 + dynamic loss scaling is kept for parity. The cast policy is
applied inside op dispatch via a thread-local AMP state consulted by
`amp_autocast` wrappers on white-listed ops.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes

# per-op lists (reference: amp white/black lists, amp/auto_cast.py)
WHITE_LIST = {
    "matmul", "linear", "conv", "conv_bias", "conv_transpose",
    "conv_transpose_bias", "einsum", "sdpa", "sdpa_mask", "sdpa_cp", "bmm",
    "mm",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax", "log_softmax", "cross_entropy", "cross_entropy_w", "mse_loss",
    "l1_loss", "norm", "sum", "mean", "cumsum", "logsumexp", "layer_norm",
    "batch_norm_train", "batch_norm_infer", "rms_norm",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.float16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


_amp = _AmpState()


def amp_state():
    return _amp


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="float16", use_promote=True):
    """Reference: paddle.amp.auto_cast (auto_cast.py:703)."""
    prev = (_amp.enabled, _amp.dtype, _amp.level, _amp.custom_white, _amp.custom_black)
    _amp.enabled = bool(enable)
    _amp.dtype = dtypes.convert_dtype(dtype)
    _amp.level = level
    _amp.custom_white = set(custom_white_list or ())
    _amp.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_amp.enabled, _amp.dtype, _amp.level, _amp.custom_white,
         _amp.custom_black) = prev


amp_guard = auto_cast


def _should_cast(op_name):
    if not _amp.enabled:
        return None
    name = op_name
    if name in _amp.custom_black or name in BLACK_LIST:
        return jnp.float32
    if _amp.level == "O2":
        return _amp.dtype
    if name in _amp.custom_white or name in WHITE_LIST:
        return _amp.dtype
    return None


from ..core.dispatch import set_amp_cast_hook

set_amp_cast_hook(_should_cast)


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None):
    """Reference: paddle.amp.decorate (auto_cast.py:787). O2 casts parameters
    to the AMP dtype (master weights live in optimizer fp32 state)."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        d = dtypes.convert_dtype(dtype)
        for m in model_list:
            for p in m.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(d)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """Dynamic loss scaling (reference: amp/grad_scaler.py:345
    `scale`, :578 `minimize`; check_finite_and_unscale kernel)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var):
        if not self._enable:
            return var
        from ..ops.math import multiply
        return multiply(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._value * inv
                p.grad._value = g
                if not bool(jnp.all(jnp.isfinite(g))):
                    found = True
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True


from . import debugging  # noqa: E402,F401
