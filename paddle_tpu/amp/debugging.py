"""AMP debugging tools (reference: python/paddle/amp/debugging.py —
enable_operator_stats_collection / collect_operator_stats printing per-op
call counts, check_numerics, compare_accuracy).

TPU-native: the dispatch profile hook already sees every eager op; the
collector counts op invocations through it (chained with any active
profiler hook), and numeric checking rides the FLAGS_check_nan_inf
sanitizer."""
from __future__ import annotations

import contextlib
from collections import Counter

from ..core import dispatch
from .. import flags as _flags

__all__ = ["collect_operator_stats", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "check_numerics",
           "operator_stats", "dump_operator_stats", "DebugMode",
           "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_layer_numerics",
           "compare_accuracy"]

_counts: Counter = Counter()
_prev_hook = None


class _CountingSpan:
    __slots__ = ("inner",)

    def __init__(self, inner):
        self.inner = inner

    def begin(self):
        if self.inner is not None:
            self.inner.begin()

    def end(self):
        if self.inner is not None:
            self.inner.end()


def _hook(name):
    _counts[name] += 1
    inner = _prev_hook(name) if _prev_hook is not None else None
    return _CountingSpan(inner)


def enable_operator_stats_collection():
    global _prev_hook
    _counts.clear()
    # chain rather than clobber: an active Profiler keeps its op spans
    _prev_hook = dispatch._profile_hook
    dispatch.set_profile_hook(_hook)


def disable_operator_stats_collection():
    global _prev_hook
    dispatch.set_profile_hook(_prev_hook)
    _prev_hook = None
    _print_stats()


def operator_stats():
    return dict(_counts)


def _print_stats():
    if not _counts:
        print("<no operators collected>")
        return
    width = max(len(k) for k in _counts) + 2
    print(f"{'op':<{width}} {'calls':>8}")
    for name, n in _counts.most_common():
        print(f"{name:<{width}} {n:>8}")


@contextlib.contextmanager
def collect_operator_stats():
    """Reference: paddle.amp.debugging.collect_operator_stats."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


@contextlib.contextmanager
def check_numerics(level=0):
    """Per-op NaN/Inf scan inside the context (reference: check_numerics /
    enable_tensor_checker). level 0 raises, 1 warns."""
    prev = _flags.get_flags(["FLAGS_check_nan_inf",
                             "FLAGS_check_nan_inf_level"])
    _flags.set_flags({"FLAGS_check_nan_inf": True,
                      "FLAGS_check_nan_inf_level": int(level)})
    try:
        yield
    finally:
        _flags.set_flags(prev)


class DebugMode:
    """Reference: amp/debugging.py DebugMode — what the tensor checker does
    on a NaN/Inf hit."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL_FOR_OVERFLOW = 2
    CHECK_ALL = 3
    CHECK_ALL_AND_ABORT = 4
    DUMP_ALL = 5


class TensorCheckerConfig:
    """Reference: amp/debugging.py TensorCheckerConfig — scope/mode for the
    model-level numeric checker (driven here by the dispatch NaN scan)."""

    def __init__(self, enable, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = bool(enable)
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


_checker_stack: list = []

_ABORT_MODES = (DebugMode.CHECK_NAN_INF_AND_ABORT,
                DebugMode.CHECK_ALL_AND_ABORT)


def enable_tensor_checker(checker_config):
    """Turn on per-op NaN/Inf checking for every dispatched op (reference:
    amp/debugging.py:634 — model-level accuracy check; here the dispatch
    layer's FLAGS_check_nan_inf scan is the checker). Calls balance with
    disable_tensor_checker like a stack, and non-abort DebugModes map to
    the warn level of the sanitizer."""
    prev = _flags.get_flags(["FLAGS_check_nan_inf",
                             "FLAGS_check_nan_inf_level"])
    _checker_stack.append(prev)
    if not checker_config.enable:
        return
    level = 0 if checker_config.debug_mode in _ABORT_MODES else 1
    _flags.set_flags({"FLAGS_check_nan_inf": True,
                      "FLAGS_check_nan_inf_level": level})


def disable_tensor_checker():
    """Restore the flags saved by the matching enable_tensor_checker
    (reference: amp/debugging.py disable_tensor_checker)."""
    if _checker_stack:
        _flags.set_flags(_checker_stack.pop())


def check_layer_numerics(func):
    """Decorator: NaN/Inf-scan a layer's forward inputs and outputs
    (reference: amp/debugging.py:64)."""
    import functools

    import numpy as np

    from ..core.tensor import Tensor

    def _flatten(v):
        if isinstance(v, (tuple, list)):
            for x in v:
                yield from _flatten(x)
        elif isinstance(v, dict):
            for x in v.values():
                yield from _flatten(x)
        else:
            yield v

    def _scan(vs, what, name):
        for v in _flatten(vs):
            if isinstance(v, Tensor):
                a = np.asarray(v._value)
                if np.issubdtype(a.dtype, np.floating) \
                        and not np.isfinite(a).all():
                    raise RuntimeError(
                        f"check_layer_numerics: NaN/Inf in {what} of "
                        f"{name}")

    @functools.wraps(func)
    def wrapper(self, *args, **kwargs):
        _scan((args, kwargs), "inputs", type(self).__name__)
        out = func(self, *args, **kwargs)
        _scan(out, "outputs", type(self).__name__)
        return out

    return wrapper


def dump_operator_stats(path):
    """Write the current collector counts as the JSONL dump
    compare_accuracy consumes (one {"op", "calls"} record per op)."""
    import json
    with open(path, "w") as f:
        for op, n in sorted(_counts.items()):
            f.write(json.dumps({"op": op, "calls": int(n)}) + "\n")


def compare_accuracy(dump_path, another_dump_path, output_filename,
                     loss_scale=1, dump_all_tensors=False):
    """Compare two operator-stats dumps (reference: amp/debugging.py:575
    compares workerlog NaN/Inf dumps). Consumes JSONL files written by
    dump_operator_stats (collect stats for each run, dump, compare) and
    reports ops whose records differ."""
    import json

    def load(p):
        out = {}
        with open(p) as f:
            for line in f:
                rec = json.loads(line)
                out[rec.get("op", "?")] = rec
        return out

    a, b = load(dump_path), load(another_dump_path)
    rows = []
    for op in sorted(set(a) | set(b)):
        ra, rb = a.get(op, {}), b.get(op, {})
        if ra != rb:
            rows.append({"op": op, "a": ra, "b": rb})
    with open(output_filename, "w") as f:
        json.dump(rows, f, indent=1)
    return rows
