"""AMP debugging tools (reference: python/paddle/amp/debugging.py —
enable_operator_stats_collection / collect_operator_stats printing per-op
call counts, check_numerics, compare_accuracy).

TPU-native: the dispatch profile hook already sees every eager op; the
collector counts op invocations through it (chained with any active
profiler hook), and numeric checking rides the FLAGS_check_nan_inf
sanitizer."""
from __future__ import annotations

import contextlib
from collections import Counter

from ..core import dispatch
from .. import flags as _flags

__all__ = ["collect_operator_stats", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "check_numerics",
           "operator_stats"]

_counts: Counter = Counter()
_prev_hook = None


class _CountingSpan:
    __slots__ = ("inner",)

    def __init__(self, inner):
        self.inner = inner

    def begin(self):
        if self.inner is not None:
            self.inner.begin()

    def end(self):
        if self.inner is not None:
            self.inner.end()


def _hook(name):
    _counts[name] += 1
    inner = _prev_hook(name) if _prev_hook is not None else None
    return _CountingSpan(inner)


def enable_operator_stats_collection():
    global _prev_hook
    _counts.clear()
    # chain rather than clobber: an active Profiler keeps its op spans
    _prev_hook = dispatch._profile_hook
    dispatch.set_profile_hook(_hook)


def disable_operator_stats_collection():
    global _prev_hook
    dispatch.set_profile_hook(_prev_hook)
    _prev_hook = None
    _print_stats()


def operator_stats():
    return dict(_counts)


def _print_stats():
    if not _counts:
        print("<no operators collected>")
        return
    width = max(len(k) for k in _counts) + 2
    print(f"{'op':<{width}} {'calls':>8}")
    for name, n in _counts.most_common():
        print(f"{name:<{width}} {n:>8}")


@contextlib.contextmanager
def collect_operator_stats():
    """Reference: paddle.amp.debugging.collect_operator_stats."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


@contextlib.contextmanager
def check_numerics(level=0):
    """Per-op NaN/Inf scan inside the context (reference: check_numerics /
    enable_tensor_checker). level 0 raises, 1 warns."""
    prev = _flags.get_flags(["FLAGS_check_nan_inf",
                             "FLAGS_check_nan_inf_level"])
    _flags.set_flags({"FLAGS_check_nan_inf": True,
                      "FLAGS_check_nan_inf_level": int(level)})
    try:
        yield
    finally:
        _flags.set_flags(prev)
