// Host event recorder: per-thread span buffers with nanosecond timestamps.
//
// Reference analog: `HostTracer`/`HostEventRecorder` (fluid/platform/
// profiler/host_tracer.h:26 — RecordEvent instrumentation writing into a
// thread-local ring buffer, merged and exported by ChromeTracingLogger).
// TPU-native role: host-side op/py spans that sit alongside XLA's own
// XPlane device traces; this records the Python-dispatch half cheaply
// (two ctypes calls per span) without holding the GIL in the recorder.
//
// Design: interned name ids; spans pushed to thread-local vectors behind a
// registry mutex only at thread-buffer creation; dump serializes everything
// to chrome-trace JSON.

#include <stdint.h>
#include <string.h>

#include <atomic>
#include <chrono>
#include <iomanip>
#include <mutex>
#include <sstream>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Span {
  uint32_t name_id;
  int64_t t0_ns;
  int64_t t1_ns;
};

struct ThreadBuf {
  uint64_t tid;
  std::mutex mu;  // owner thread writes, dump/clear read — must exclude
  std::vector<Span> spans;
  std::vector<std::pair<uint32_t, int64_t>> stack;  // open spans
};

std::mutex g_mu;
std::vector<ThreadBuf*> g_bufs;
std::unordered_map<std::string, uint32_t> g_name_ids;
std::vector<std::string> g_names;
std::atomic<bool> g_enabled{false};
std::atomic<uint64_t> g_next_tid{0};

ThreadBuf* tls() {
  thread_local ThreadBuf* buf = [] {
    auto* b = new ThreadBuf();
    b->tid = g_next_tid.fetch_add(1);
    std::lock_guard<std::mutex> g(g_mu);
    g_bufs.push_back(b);
    return b;
  }();
  return buf;
}

uint32_t intern(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_name_ids.find(name);
  if (it != g_name_ids.end()) return it->second;
  uint32_t id = static_cast<uint32_t>(g_names.size());
  g_names.emplace_back(name);
  g_name_ids.emplace(name, id);
  return id;
}

}  // namespace

extern "C" {

void pht_enable() { g_enabled.store(true, std::memory_order_relaxed); }
void pht_disable() { g_enabled.store(false, std::memory_order_relaxed); }
int pht_enabled() { return g_enabled.load(std::memory_order_relaxed); }

void pht_clear() {
  std::lock_guard<std::mutex> g(g_mu);
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    b->spans.clear();
    b->stack.clear();
  }
}

// Returns an interned id usable with pht_begin_id (amortizes interning).
uint32_t pht_name_id(const char* name) { return intern(name); }

void pht_begin_id(uint32_t name_id) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  auto* b = tls();
  std::lock_guard<std::mutex> g(b->mu);  // uncontended fast path
  b->stack.emplace_back(name_id, now_ns());
}

void pht_begin(const char* name) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  pht_begin_id(intern(name));
}

void pht_end() {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  auto* b = tls();
  std::lock_guard<std::mutex> g(b->mu);
  if (b->stack.empty()) return;
  auto open = b->stack.back();
  b->stack.pop_back();
  b->spans.push_back(Span{open.first, open.second, now_ns()});
}

// One-shot complete span (begin+end supplied by caller, ns).
void pht_span(const char* name, int64_t t0_ns, int64_t t1_ns) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  uint32_t id = intern(name);
  auto* b = tls();
  std::lock_guard<std::mutex> g(b->mu);
  b->spans.push_back(Span{id, t0_ns, t1_ns});
}

int64_t pht_now_ns() { return now_ns(); }

// Serialize all spans as chrome-trace "X" events (JSON array body).
// Caller frees with pht_free.
char* pht_dump_json(int pid) {
  std::lock_guard<std::mutex> g(g_mu);
  std::ostringstream os;
  // default 6-sig-digit doubles collapse ~1e12-ns timestamps; chrome trace
  // wants microseconds — emit with fixed sub-us precision
  os << std::fixed << std::setprecision(3);
  os << "[";
  bool first = true;
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    for (auto& s : b->spans) {
      if (!first) os << ",";
      first = false;
      const std::string& nm = g_names[s.name_id];
      std::string esc;
      esc.reserve(nm.size());
      for (char c : nm) {
        if (c == '"' || c == '\\') esc.push_back('\\');
        esc.push_back(c);
      }
      os << "{\"ph\":\"X\",\"pid\":" << pid << ",\"tid\":" << b->tid
         << ",\"name\":\"" << esc << "\",\"ts\":" << s.t0_ns / 1000.0
         << ",\"dur\":" << (s.t1_ns - s.t0_ns) / 1000.0 << "}";
    }
  }
  os << "]";
  std::string out = os.str();
  char* p = static_cast<char*>(malloc(out.size() + 1));
  memcpy(p, out.data(), out.size() + 1);
  return p;
}

// Binary dump: per span (tid u64, name_id u32, t0 i64, t1 i64); returns
// count, fills *out (caller frees). Names via pht_get_name.
int64_t pht_dump_raw(char** out) {
  std::lock_guard<std::mutex> g(g_mu);
  std::vector<std::pair<uint64_t, Span>> all;
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> gb(b->mu);
    for (auto& s : b->spans) all.emplace_back(b->tid, s);
  }
  const size_t rec = 8 + 4 + 8 + 8;
  char* p = static_cast<char*>(malloc(all.size() * rec + 1));
  char* q = p;
  for (auto& ts : all) {
    memcpy(q, &ts.first, 8);
    memcpy(q + 8, &ts.second.name_id, 4);
    memcpy(q + 12, &ts.second.t0_ns, 8);
    memcpy(q + 20, &ts.second.t1_ns, 8);
    q += rec;
  }
  *out = p;
  return static_cast<int64_t>(all.size());
}

// malloc'd copy (free with pht_free): interior string pointers are not
// stable across concurrent interning
char* pht_get_name(uint32_t id) {
  std::lock_guard<std::mutex> g(g_mu);
  std::string nm = id < g_names.size() ? g_names[id] : std::string();
  char* p = static_cast<char*>(malloc(nm.size() + 1));
  memcpy(p, nm.c_str(), nm.size() + 1);
  return p;
}

void pht_free(char* p) { free(p); }

}  // extern "C"
