// Native data feeder: multi-threaded file readers + batch assembly.
//
// Reference analog: Paddle's C++ `DataFeed`/`Dataset` ingest pipeline
// (fluid/framework/data_feed.cc, data_set.cc) that parses training files
// and assembles batches in worker threads, feeding trainers without
// touching Python. TPU-native role: host-side input pipeline that keeps
// the one controller process's Python thread free while batches of
// fixed-size records (e.g. pre-tokenized [seq_len] int32 sequences) are
// read, shuffled and packed off-GIL; Python pops ready batches and ships
// them to the chip.
//
// Design: N reader threads pull file shards from a work queue, slice them
// into records, optionally shuffle within a read block, and push packed
// batch buffers into a bounded ring; `ptf_next` blocks until a batch (or
// end-of-epoch). C ABI for ctypes.

#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Batch {
  char* data;
  int64_t size;
};

class Feeder {
 public:
  Feeder(std::vector<std::string> paths, int64_t record_bytes,
         int64_t batch_size, int threads, uint64_t seed, bool shuffle,
         bool drop_last, int64_t queue_capacity)
      : paths_(std::move(paths)),
        record_bytes_(record_bytes),
        batch_size_(batch_size),
        shuffle_(shuffle),
        drop_last_(drop_last),
        capacity_(queue_capacity),
        seed_(seed) {
    if (shuffle_) {
      std::mt19937_64 rng(seed_);
      std::shuffle(paths_.begin(), paths_.end(), rng);
    }
    next_path_.store(0);
    live_readers_.store(threads);
    for (int i = 0; i < threads; i++)
      readers_.emplace_back([this, i] { ReadLoop(i); });
  }

  ~Feeder() { Stop(); }

  void Stop() {
    stop_.store(true);
    cv_pop_.notify_all();
    cv_push_.notify_all();
    for (auto& t : readers_)
      if (t.joinable()) t.join();
    readers_.clear();
    std::lock_guard<std::mutex> g(mu_);
    for (auto& b : queue_) free(b.data);
    queue_.clear();
    if (partial_.data) {
      free(partial_.data);
      partial_ = Batch{nullptr, 0};
    }
  }

  // Returns >0 size, -1 end of data, -2 timeout.
  int64_t Next(char** out, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    bool ok = cv_pop_.wait_for(
        lk, std::chrono::milliseconds(timeout_ms), [this] {
          return !queue_.empty() || stop_.load() ||
                 (live_readers_.load() == 0 && queue_.empty());
        });
    if (!ok) return -2;
    if (!queue_.empty()) {
      Batch b = queue_.front();
      queue_.pop_front();
      cv_push_.notify_one();
      *out = b.data;
      return b.size;
    }
    return -1;  // drained and all readers finished (or stopped)
  }

 private:
  void ReadLoop(int tid) {
    std::mt19937_64 rng(seed_ + 0x9e3779b97f4a7c15ull * (tid + 1));
    std::vector<char> carry;  // partial record/batch spill between files
    while (!stop_.load()) {
      size_t idx = next_path_.fetch_add(1);
      if (idx >= paths_.size()) break;
      FILE* f = fopen(paths_[idx].c_str(), "rb");
      if (!f) continue;
      // read the whole shard in large blocks, slice into records
      const size_t kBlock = size_t(4) << 20;
      std::vector<char> buf;
      buf.reserve(kBlock + carry.size());
      buf = std::move(carry);
      carry.clear();
      for (;;) {
        size_t off = buf.size();
        buf.resize(off + kBlock);
        size_t got = fread(buf.data() + off, 1, kBlock, f);
        buf.resize(off + got);
        bool eof = got < kBlock;
        size_t usable = buf.size() - buf.size() % record_bytes_;
        if (eof || usable >= kBlock) {
          EmitRecords(buf.data(), usable / record_bytes_, &rng);
          std::vector<char> rest(buf.begin() + usable, buf.end());
          buf = std::move(rest);
        }
        if (eof) break;
        if (stop_.load()) break;
      }
      carry = std::move(buf);  // partial record crosses file boundary
      fclose(f);
    }
    if (live_readers_.fetch_sub(1) == 1) {
      // last reader out: flush the partial batch unless drop_last
      std::lock_guard<std::mutex> g(mu_);
      if (!drop_last_ && partial_.size > 0) {
        queue_.push_back(partial_);
        partial_ = Batch{nullptr, 0};
      } else if (partial_.data) {
        free(partial_.data);
        partial_ = Batch{nullptr, 0};
      }
    }
    cv_pop_.notify_all();
  }

  // Pack n records (contiguous at p) into batches; shuffle record order
  // within this block first (block-local shuffle ≈ reference Dataset's
  // shuffle window).
  void EmitRecords(const char* p, size_t n, std::mt19937_64* rng) {
    std::vector<uint32_t> order(n);
    for (size_t i = 0; i < n; i++) order[i] = static_cast<uint32_t>(i);
    if (shuffle_) std::shuffle(order.begin(), order.end(), *rng);
    const int64_t bbytes = batch_size_ * record_bytes_;
    size_t i = 0;
    while (i < n && !stop_.load()) {
      std::unique_lock<std::mutex> lk(mu_);  // one acquisition per batch
      if (!partial_.data) {
        partial_.data = static_cast<char*>(malloc(bbytes));
        partial_.size = 0;
      }
      while (i < n && partial_.size < bbytes) {
        memcpy(partial_.data + partial_.size, p + order[i] * record_bytes_,
               record_bytes_);
        partial_.size += record_bytes_;
        i++;
      }
      if (partial_.size == bbytes) {
        cv_push_.wait(lk, [this] {
          return queue_.size() < static_cast<size_t>(capacity_) ||
                 stop_.load();
        });
        if (stop_.load()) return;
        // another reader may have pushed this batch while we waited on a
        // full queue — only push if the full partial is still in place
        if (partial_.data && partial_.size == bbytes) {
          queue_.push_back(partial_);
          partial_ = Batch{nullptr, 0};
          cv_pop_.notify_one();
        }
      }
    }
  }

  std::vector<std::string> paths_;
  const int64_t record_bytes_, batch_size_;
  const bool shuffle_, drop_last_;
  const int64_t capacity_;
  const uint64_t seed_;
  std::vector<std::thread> readers_;
  std::atomic<size_t> next_path_;
  std::atomic<int> live_readers_;
  std::atomic<bool> stop_{false};
  std::mutex mu_;
  std::condition_variable cv_pop_, cv_push_;
  std::deque<Batch> queue_;
  Batch partial_{nullptr, 0};
};

}  // namespace

extern "C" {

void* ptf_start(const char* paths_joined, int64_t record_bytes,
                int64_t batch_size, int threads, uint64_t seed, int shuffle,
                int drop_last, int64_t queue_capacity) {
  std::vector<std::string> paths;
  const char* p = paths_joined;
  while (*p) {
    const char* nl = strchr(p, '\n');
    if (!nl) {
      paths.emplace_back(p);
      break;
    }
    if (nl != p) paths.emplace_back(p, nl - p);
    p = nl + 1;
  }
  if (paths.empty() || record_bytes <= 0 || batch_size <= 0) return nullptr;
  return new Feeder(std::move(paths), record_bytes, batch_size,
                    std::max(1, threads), seed, shuffle != 0, drop_last != 0,
                    std::max<int64_t>(1, queue_capacity));
}

int64_t ptf_next(void* h, char** out, int64_t timeout_ms) {
  return static_cast<Feeder*>(h)->Next(out, timeout_ms);
}

void ptf_free_batch(char* p) { free(p); }

void ptf_stop(void* h) { delete static_cast<Feeder*>(h); }

}  // extern "C"
