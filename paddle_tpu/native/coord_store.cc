// Coordination key-value store for multi-host DCN bootstrap.
//
// Reference analog: Paddle's TCPStore (phi/core/distributed/store/
// tcp_store.h:121 — rank0-hosted TCP KV with set/get/add/wait/barrier,
// MasterDaemon in tcp_store.cc) plus the comm watchdog's liveness tracking
// (comm_task_manager.h:37). TPU-native role: the control-plane bootstrap +
// failure detector that sits NEXT TO the XLA/ICI data plane (which needs no
// explicit comm objects) — mesh rendezvous, elastic membership, barriers.
//
// Design (not a translation): one poll()-driven single-threaded daemon —
// no thread-per-connection, no locks on the hot path; clients speak a
// length-prefixed binary protocol; WAIT parks the client on an in-daemon
// waitlist woken by SET/ADD (the reference blocks a dedicated reply
// thread). Heartbeats are ordinary keys with server-side receipt
// timestamps, so the watchdog is a pure reader.
//
// C ABI only (consumed via ctypes from python — no pybind11 in this
// image).

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <stdint.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Cmd : uint8_t {
  CMD_SET = 1,
  CMD_GET = 2,
  CMD_ADD = 3,
  CMD_WAIT = 4,    // block until key exists
  CMD_DELETE = 5,
  CMD_KEYS = 6,    // list keys with a prefix
  CMD_STAMP = 7,   // server-receipt age query: ms since key last written
  CMD_PING = 8,
};

enum Status : uint8_t {
  ST_OK = 0,
  ST_NOT_FOUND = 1,
  ST_ERROR = 2,
};

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// ---- wire helpers (blocking fd) -------------------------------------------

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) {
      if (k < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) {
      if (k < 0 && errno == EINTR) continue;
      return false;
    }
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool send_u32(int fd, uint32_t v) {
  uint32_t be = htonl(v);
  return send_all(fd, &be, 4);
}

bool recv_u32(int fd, uint32_t* v) {
  uint32_t be;
  if (!recv_all(fd, &be, 4)) return false;
  *v = ntohl(be);
  return true;
}

bool send_bytes(int fd, const std::string& s) {
  return send_u32(fd, static_cast<uint32_t>(s.size())) &&
         (s.empty() || send_all(fd, s.data(), s.size()));
}

bool recv_bytes(int fd, std::string* s) {
  uint32_t n;
  if (!recv_u32(fd, &n)) return false;
  if (n > (64u << 20)) return false;  // sanity cap: 64 MiB per value
  s->resize(n);
  return n == 0 || recv_all(fd, &(*s)[0], n);
}

// ---- server ---------------------------------------------------------------

struct Entry {
  std::string value;
  int64_t stamp_ms = 0;  // server receipt time of last write
};

class Daemon {
 public:
  explicit Daemon(int port) : port_(port) {}

  // Returns bound port (for port=0 auto-assign), or -1 on failure.
  int Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return -1;
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port_));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
            0 ||
        listen(listen_fd_, 128) < 0) {
      ::close(listen_fd_);
      return -1;
    }
    socklen_t len = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    running_.store(true);
    thread_ = std::thread([this] { Loop(); });
    return port_;
  }

  void Stop() {
    running_.store(false);
    // nudge the poll loop awake via a self-connection
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd >= 0) {
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
      addr.sin_port = htons(static_cast<uint16_t>(port_));
      connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
      ::close(fd);
    }
    if (thread_.joinable()) thread_.join();
    ::close(listen_fd_);
    for (auto& c : clients_) ::close(c.fd);
    clients_.clear();
  }

  int port() const { return port_; }

 private:
  struct Client {
    int fd;
    // a WAITing client is parked here until its key appears
    bool waiting = false;
    std::string wait_key;
    int64_t wait_deadline_ms = 0;  // 0 = forever
  };

  void Loop() {
    while (running_.load()) {
      std::vector<pollfd> pfds;
      pfds.push_back({listen_fd_, POLLIN, 0});
      for (auto& c : clients_)
        pfds.push_back({c.fd, static_cast<short>(c.waiting ? 0 : POLLIN), 0});
      // bounded poll so parked WAIT timeouts fire
      ::poll(pfds.data(), pfds.size(), 50);
      if (!running_.load()) break;
      if (pfds[0].revents & POLLIN) Accept();
      // iterate over a snapshot: Serve() may append (never removes)
      size_t n = clients_.size();
      std::vector<size_t> dead;
      for (size_t i = 0; i < n && i + 1 < pfds.size(); i++) {
        auto& c = clients_[i];
        if (c.waiting) {
          if (TryWake(&c)) continue;
          if (c.wait_deadline_ms && now_ms() > c.wait_deadline_ms) {
            uint8_t st = ST_NOT_FOUND;
            send_all(c.fd, &st, 1);
            c.waiting = false;
          }
          continue;
        }
        if (pfds[i + 1].revents & (POLLIN | POLLHUP | POLLERR)) {
          if (!Serve(&c)) dead.push_back(i);
        }
      }
      for (auto it = dead.rbegin(); it != dead.rend(); ++it) {
        ::close(clients_[*it].fd);
        clients_.erase(clients_.begin() + static_cast<long>(*it));
      }
    }
  }

  void Accept() {
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    clients_.push_back(Client{fd});
  }

  bool TryWake(Client* c) {
    auto it = data_.find(c->wait_key);
    if (it == data_.end()) return false;
    uint8_t st = ST_OK;
    if (!send_all(c->fd, &st, 1) || !send_bytes(c->fd, it->second.value)) {
      // connection died mid-wake: resume POLLIN so the next loop reaps it
      c->waiting = false;
      return false;
    }
    c->waiting = false;
    return true;
  }

  bool Serve(Client* c) {
    uint8_t cmd;
    if (!recv_all(c->fd, &cmd, 1)) return false;
    switch (cmd) {
      case CMD_SET: {
        std::string key, val;
        if (!recv_bytes(c->fd, &key) || !recv_bytes(c->fd, &val)) return false;
        data_[key] = Entry{std::move(val), now_ms()};
        uint8_t st = ST_OK;
        return send_all(c->fd, &st, 1);
      }
      case CMD_GET: {
        std::string key;
        if (!recv_bytes(c->fd, &key)) return false;
        auto it = data_.find(key);
        uint8_t st = it == data_.end() ? ST_NOT_FOUND : ST_OK;
        if (!send_all(c->fd, &st, 1)) return false;
        if (st == ST_OK) return send_bytes(c->fd, it->second.value);
        return true;
      }
      case CMD_ADD: {
        std::string key;
        int64_t delta;
        if (!recv_bytes(c->fd, &key) || !recv_all(c->fd, &delta, 8))
          return false;
        int64_t cur = 0;
        auto it = data_.find(key);
        if (it != data_.end() && !it->second.value.empty())
          cur = strtoll(it->second.value.c_str(), nullptr, 10);
        cur += delta;
        data_[key] = Entry{std::to_string(cur), now_ms()};
        uint8_t st = ST_OK;
        return send_all(c->fd, &st, 1) && send_all(c->fd, &cur, 8);
      }
      case CMD_WAIT: {
        std::string key;
        int64_t timeout_ms;
        if (!recv_bytes(c->fd, &key) || !recv_all(c->fd, &timeout_ms, 8))
          return false;
        c->wait_key = key;
        c->wait_deadline_ms = timeout_ms > 0 ? now_ms() + timeout_ms : 0;
        c->waiting = true;
        TryWake(c);  // may satisfy immediately
        return true;
      }
      case CMD_DELETE: {
        std::string key;
        if (!recv_bytes(c->fd, &key)) return false;
        uint8_t st = data_.erase(key) ? ST_OK : ST_NOT_FOUND;
        return send_all(c->fd, &st, 1);
      }
      case CMD_KEYS: {
        std::string prefix;
        if (!recv_bytes(c->fd, &prefix)) return false;
        std::string joined;
        for (auto& kv : data_) {
          if (kv.first.compare(0, prefix.size(), prefix) == 0) {
            joined += kv.first;
            joined += '\n';
          }
        }
        uint8_t st = ST_OK;
        return send_all(c->fd, &st, 1) && send_bytes(c->fd, joined);
      }
      case CMD_STAMP: {
        std::string key;
        if (!recv_bytes(c->fd, &key)) return false;
        auto it = data_.find(key);
        uint8_t st = it == data_.end() ? ST_NOT_FOUND : ST_OK;
        int64_t age = it == data_.end() ? -1 : now_ms() - it->second.stamp_ms;
        return send_all(c->fd, &st, 1) && send_all(c->fd, &age, 8);
      }
      case CMD_PING: {
        uint8_t st = ST_OK;
        return send_all(c->fd, &st, 1);
      }
      default:
        return false;
    }
  }

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread thread_;
  std::vector<Client> clients_;               // daemon-thread-only
  std::map<std::string, Entry> data_;         // daemon-thread-only (ordered
                                              // for prefix listing)
};

// ---- client ---------------------------------------------------------------

class StoreClient {
 public:
  // each call opens its own request/response exchange on one persistent
  // connection; a mutex serializes callers (heartbeat thread + user thread)
  bool Connect(const std::string& host, int port, int64_t timeout_ms) {
    host_ = host;
    port_ = port;
    // resolve hostnames too (masters are usually named hosts, not IPs)
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), std::to_string(port).c_str(), &hints,
                    &res) != 0 ||
        res == nullptr)
      return false;
    int64_t deadline = now_ms() + timeout_ms;
    bool ok = false;
    do {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd_ < 0) break;
      if (connect(fd_, res->ai_addr, res->ai_addrlen) == 0) {
        int one = 1;
        setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        ok = true;
        break;
      }
      ::close(fd_);
      fd_ = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    } while (now_ms() < deadline);
    freeaddrinfo(res);
    return ok;
  }

  void Close() {
    std::lock_guard<std::mutex> g(mu_);
    if (fd_ >= 0) ::close(fd_);
    fd_ = -1;
  }

  int Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_SET, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_bytes(fd_, val) || !recv_all(fd_, &st, 1))
      return -1;
    return st == ST_OK ? 0 : -1;
  }

  int Get(const std::string& key, std::string* val) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_GET, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !recv_all(fd_, &st, 1))
      return -1;
    if (st != ST_OK) return 1;  // not found
    return recv_bytes(fd_, val) ? 0 : -1;
  }

  int Add(const std::string& key, int64_t delta, int64_t* out) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_ADD, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_all(fd_, &delta, 8) || !recv_all(fd_, &st, 1) ||
        !recv_all(fd_, out, 8))
      return -1;
    return st == ST_OK ? 0 : -1;
  }

  int Wait(const std::string& key, int64_t timeout_ms, std::string* val) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_WAIT, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !send_all(fd_, &timeout_ms, 8) || !recv_all(fd_, &st, 1))
      return -1;
    if (st != ST_OK) return 1;  // timed out
    return recv_bytes(fd_, val) ? 0 : -1;
  }

  int Delete(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_DELETE, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !recv_all(fd_, &st, 1))
      return -1;
    return st == ST_OK ? 0 : 1;
  }

  int Keys(const std::string& prefix, std::string* joined) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_KEYS, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, prefix) ||
        !recv_all(fd_, &st, 1) || !recv_bytes(fd_, joined))
      return -1;
    return 0;
  }

  int StampAge(const std::string& key, int64_t* age_ms) {
    std::lock_guard<std::mutex> g(mu_);
    uint8_t cmd = CMD_STAMP, st;
    if (!send_all(fd_, &cmd, 1) || !send_bytes(fd_, key) ||
        !recv_all(fd_, &st, 1) || !recv_all(fd_, age_ms, 8))
      return -1;
    return st == ST_OK ? 0 : 1;
  }

  // ---- heartbeat publisher (the watchdog's write side) ----
  // Runs on its OWN connection: the main connection's mutex is held for
  // the full duration of a parked Wait/barrier, and a rank sitting at a
  // barrier must keep heartbeating or the watchdog declares it dead.
  void StartHeartbeat(const std::string& key, int64_t interval_ms) {
    StopHeartbeat();
    hb_run_.store(true);
    std::string host = host_;
    int port = port_;
    hb_thread_ = std::thread([this, key, interval_ms, host, port] {
      StoreClient hb;
      bool connected = false;
      while (hb_run_.load()) {
        if (!connected) connected = hb.Connect(host, port, 2000);
        if (connected && hb.Set(key, std::to_string(now_ms())) != 0) {
          // connection broke: reconnect on the next beat
          hb.Close();
          connected = false;
        }
        std::unique_lock<std::mutex> lk(hb_mu_);
        hb_cv_.wait_for(lk, std::chrono::milliseconds(interval_ms),
                        [this] { return !hb_run_.load(); });
      }
      hb.Close();
    });
  }

  void StopHeartbeat() {
    hb_run_.store(false);
    hb_cv_.notify_all();
    if (hb_thread_.joinable()) hb_thread_.join();
  }

 private:
  int fd_ = -1;
  std::string host_;
  int port_ = 0;
  std::mutex mu_;
  std::thread hb_thread_;
  std::atomic<bool> hb_run_{false};
  std::mutex hb_mu_;
  std::condition_variable hb_cv_;
};

}  // namespace

// ---- C ABI ----------------------------------------------------------------

extern "C" {

void* pts_server_start(int port, int* bound_port) {
  auto* d = new Daemon(port);
  int p = d->Start();
  if (p < 0) {
    delete d;
    return nullptr;
  }
  if (bound_port) *bound_port = p;
  return d;
}

void pts_server_stop(void* h) {
  auto* d = static_cast<Daemon*>(h);
  d->Stop();
  delete d;
}

void* pts_connect(const char* host, int port, int64_t timeout_ms) {
  auto* c = new StoreClient();
  if (!c->Connect(host, port, timeout_ms)) {
    delete c;
    return nullptr;
  }
  return c;
}

void pts_close(void* h) {
  auto* c = static_cast<StoreClient*>(h);
  c->StopHeartbeat();
  c->Close();
  delete c;
}

int pts_set(void* h, const char* key, const char* val, int val_len) {
  return static_cast<StoreClient*>(h)->Set(key, std::string(val, val_len));
}

// Returns length (>=0) or -1 error / -2 not found. Caller frees via
// pts_free_buf.
int pts_get(void* h, const char* key, char** out) {
  std::string v;
  int rc = static_cast<StoreClient*>(h)->Get(key, &v);
  if (rc != 0) return rc < 0 ? -1 : -2;
  *out = static_cast<char*>(malloc(v.size() + 1));
  memcpy(*out, v.data(), v.size());
  (*out)[v.size()] = 0;
  return static_cast<int>(v.size());
}

int pts_wait(void* h, const char* key, int64_t timeout_ms, char** out) {
  std::string v;
  int rc = static_cast<StoreClient*>(h)->Wait(key, timeout_ms, &v);
  if (rc != 0) return rc < 0 ? -1 : -2;
  *out = static_cast<char*>(malloc(v.size() + 1));
  memcpy(*out, v.data(), v.size());
  (*out)[v.size()] = 0;
  return static_cast<int>(v.size());
}

void pts_free_buf(char* p) { free(p); }

int64_t pts_add(void* h, const char* key, int64_t delta) {
  int64_t out = 0;
  if (static_cast<StoreClient*>(h)->Add(key, delta, &out) != 0) return -1;
  return out;
}

int pts_delete(void* h, const char* key) {
  return static_cast<StoreClient*>(h)->Delete(key);
}

int pts_keys(void* h, const char* prefix, char** out) {
  std::string v;
  if (static_cast<StoreClient*>(h)->Keys(prefix, &v) != 0) return -1;
  *out = static_cast<char*>(malloc(v.size() + 1));
  memcpy(*out, v.data(), v.size());
  (*out)[v.size()] = 0;
  return static_cast<int>(v.size());
}

// ms since last write of key; -1 not found / error.
int64_t pts_stamp_age_ms(void* h, const char* key) {
  int64_t age = -1;
  if (static_cast<StoreClient*>(h)->StampAge(key, &age) != 0) return -1;
  return age;
}

void pts_heartbeat_start(void* h, const char* key, int64_t interval_ms) {
  static_cast<StoreClient*>(h)->StartHeartbeat(key, interval_ms);
}

void pts_heartbeat_stop(void* h) {
  static_cast<StoreClient*>(h)->StopHeartbeat();
}

}  // extern "C"
