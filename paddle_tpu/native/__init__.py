"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes (no pybind11 in this environment).

Reference analog: Paddle ships its control plane (TCPStore, watchdog, data
feeders) as C++ inside libpaddle; here each component is a small shared
library compiled at first use and cached next to the source (keyed by a
source hash, so edits rebuild automatically)."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_lock = threading.Lock()
_libs = {}


def build_and_load(name: str, extra_flags=()) -> ctypes.CDLL:
    """Compile native/<name>.cc to a cached .so and dlopen it."""
    with _lock:
        if name in _libs:
            return _libs[name]
        src = os.path.join(_DIR, name + ".cc")
        with open(src, "rb") as f:
            tag = hashlib.sha256(f.read()).hexdigest()[:16]
        os.makedirs(_BUILD, exist_ok=True)
        so = os.path.join(_BUILD, f"lib{name}-{tag}.so")
        if not os.path.exists(so):
            tmp = so + f".tmp{os.getpid()}"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", "-o", tmp, src, *extra_flags]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    f"native build of {name} failed:\n{e.stderr}") from e
            os.replace(tmp, so)  # atomic vs concurrent builders
        lib = ctypes.CDLL(so)
        _libs[name] = lib
        return lib
