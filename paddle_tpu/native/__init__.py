"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes (no pybind11 in this environment).

Reference analog: Paddle ships its control plane (TCPStore, watchdog, data
feeders) as C++ inside libpaddle; here each component is a small shared
library compiled at first use and cached next to the source (keyed by a
source hash, so edits rebuild automatically)."""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_lock = threading.Lock()
_libs = {}


def build_sources(name: str, sources, extra_flags=(),
                  build_dir=None) -> ctypes.CDLL:
    """Compile arbitrary C++ sources to a cached .so and dlopen it
    (shared by the built-in components and user cpp_extension ops)."""
    with _lock:
        h = hashlib.sha256()
        for src in sources:
            with open(src, "rb") as f:
                h.update(f.read())
        h.update(" ".join(extra_flags).encode())
        tag = h.hexdigest()[:16]
        key = (name, tag, build_dir)
        if key in _libs:
            return _libs[key]
        out_dir = build_dir or _BUILD
        os.makedirs(out_dir, exist_ok=True)
        so = os.path.join(out_dir, f"lib{name}-{tag}.so")
        if not os.path.exists(so):
            tmp = so + f".tmp{os.getpid()}"
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", "-o", tmp, *sources, *extra_flags]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except subprocess.CalledProcessError as e:
                raise RuntimeError(
                    f"native build of {name} failed:\n{e.stderr}") from e
            os.replace(tmp, so)  # atomic vs concurrent builders
        lib = ctypes.CDLL(so)
        _libs[key] = lib
        return lib


def build_and_load(name: str, extra_flags=()) -> ctypes.CDLL:
    """Compile native/<name>.cc to a cached .so and dlopen it."""
    return build_sources(name, [os.path.join(_DIR, name + ".cc")],
                         extra_flags)
