"""Static-graph surface: op capture + replaying Executor.

Reference: python/paddle/static/ — Program (framework.py:5736) records ops
appended by the layer calls between `enable_static()` and `Executor.run`
(base/executor.py:1152), which then interprets the op list against a feed
dict and returns fetches.

TPU-native redesign: while static mode is on, every eager dispatch
(core/dispatch.py:_apply) ALSO appends (impl, statics, input-refs,
output-ids) to the default Program — the op-list IR is captured from the
same pure-jnp impls the eager mode runs, not from a separate operator
registry. `Executor.run` replays that list with the feed substituted:

- inference fetches replay as ONE jitted program (the whole captured op
  list traces into a single XLA executable, cached per feed signature);
- when `optimizer.minimize(loss)` was captured, run() replays eagerly
  through the autograd tape against the *live* parameter tensors, then
  backprops and steps — one exe.run == one training step, reference
  semantics (executor.py `run(main_program, feed, fetch_list)`).

Anything run() cannot honor (unknown fetch, missing feed) raises loudly —
never echoes the fetch list back.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core import dispatch as _dispatch

_static_mode = [False]
_capture_suspended = [0]


def _enable():
    _static_mode[0] = True
    _dispatch.set_static_capture_hook(_capture_op)


def _disable():
    _static_mode[0] = False
    _dispatch.set_static_capture_hook(None)


def _static_enabled():
    return _static_mode[0]


@contextlib.contextmanager
def _suspend_capture():
    _capture_suspended[0] += 1
    try:
        yield
    finally:
        _capture_suspended[0] -= 1


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class Program:
    """Captured op-list program. `_ops` entries:
    (name, impl, statics, in_refs, out_ids) where in_refs are
    ('v', tensor_id) | ('c', raw_value)."""

    def __init__(self):
        self._ops = []
        self._tensors = {}        # tensor_id -> Tensor (live handles)
        self._feed_vars = {}      # name -> placeholder Tensor
        self._minimize = None     # (optimizer, loss Tensor)

    # -- capture --------------------------------------------------------
    def _record(self, name, impl, statics, tensor_args, outs):
        in_refs = []
        for t in tensor_args:
            if isinstance(t, Tensor):
                in_refs.append(("v", id(t)))
                self._tensors[id(t)] = t
            else:
                in_refs.append(("c", t))
        out_ids = []
        for o in outs:
            out_ids.append(id(o))
            self._tensors[id(o)] = o
        self._ops.append((name, impl, statics, in_refs, out_ids))

    def _register_minimize(self, optimizer, loss):
        self._minimize = (optimizer, loss)

    # -- reference API surface ------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        if not for_test:
            return self
        p = Program()
        p._ops = list(self._ops)
        p._tensors = dict(self._tensors)
        p._feed_vars = dict(self._feed_vars)
        p._minimize = None  # the eval clone drops the training hook
        return p

    def list_vars(self):
        return list(self._tensors.values())

    @property
    def num_ops(self):
        return len(self._ops)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[-1]


def default_startup_program():
    return _default_startup[-1]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Reference: static.program_guard (framework.py:7436)."""
    _default_main.append(main_program)
    _default_startup.append(startup_program or Program())
    try:
        yield
    finally:
        _default_main.pop()
        _default_startup.pop()


def _capture_op(name, impl, statics, tensor_args, outs):
    if not _static_mode[0] or _capture_suspended[0]:
        return
    default_main_program()._record(name, impl, statics, tensor_args, outs)


def data(name, shape, dtype="float32", lod_level=0):
    """Reference: static.data — a feed placeholder. The returned Tensor
    carries a zero value at build time (shape propagation runs through the
    real kernels); dynamic dims (None/-1) build at size 1 and re-jit per
    fed batch size."""
    shape = [1 if (s is None or (isinstance(s, int) and s < 0)) else s
             for s in shape]
    t = Tensor(jnp.zeros(shape, dtypes.convert_dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    default_main_program()._feed_vars[name] = t
    default_main_program()._tensors[id(t)] = t
    return t


class Executor:
    """Reference: static.Executor (base/executor.py:1152)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        program = program or default_main_program()
        feed = feed or {}
        if fetch_list is None or not fetch_list:
            # startup-program run: parameters initialize eagerly on this
            # stack, so there is nothing to execute.
            if program._ops and feed:
                raise RuntimeError(
                    "Executor.run with feed but no fetch_list: pass the "
                    "variables to fetch")
            return []
        if not program._ops:
            raise NotImplementedError(
                "Executor.run: this Program captured no ops — build the "
                "graph between paddle.enable_static() and run(), or use "
                "the eager/jit path")
        if program._minimize is not None:
            return self._run_train(program, feed, fetch_list)
        return self._run_jitted(program, feed, fetch_list)

    # -- training replay (eager tape against live parameters) -----------
    def _run_train(self, program, feed, fetch_list):
        env = self._replay_eager(program, feed)
        out = self._collect(program, env, fetch_list, numpy=False)
        opt, loss_var = program._minimize
        loss_t = env.get(id(loss_var))
        if loss_t is None:
            raise RuntimeError(
                "Executor.run: minimize() loss is not produced by this "
                "program's ops")
        with _suspend_capture():
            loss_t.backward()
            opt.step()
            opt.clear_grad()
        return [np.asarray(o._value) if isinstance(o, Tensor) else o
                for o in out]

    def _replay_eager(self, program, feed):
        env = {}
        for name, ph in program._feed_vars.items():
            if name not in feed:
                raise KeyError(
                    f"Executor.run: feed is missing '{name}' "
                    f"(declared by static.data)")
            v = feed[name]
            v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            t = Tensor(v)
            t.stop_gradient = True
            env[id(ph)] = t
        with _suspend_capture():
            for op_name, impl, statics, in_refs, out_ids in program._ops:
                args = []
                for kind, ref in in_refs:
                    if kind == "c":
                        args.append(ref)
                    elif ref in env:
                        args.append(env[ref])
                    else:
                        args.append(program._tensors[ref])  # live external
                out = _dispatch.apply(op_name, impl, args, statics)
                outs = out if isinstance(out, tuple) else (out,)
                for oid, o in zip(out_ids, outs):
                    env[oid] = o
        return env

    def _collect(self, program, env, fetch_list, numpy=True):
        out = []
        for f in fetch_list:
            if isinstance(f, str):
                ph = program._feed_vars.get(f)
                named = [t for t in program._tensors.values()
                         if getattr(t, "name", None) == f]
                f = ph if ph is not None else (named[0] if named else None)
            if not isinstance(f, Tensor):
                raise TypeError(
                    f"Executor.run: cannot fetch {f!r} — fetch_list entries "
                    f"must be program variables")
            t = env.get(id(f), f if id(f) in program._tensors else None)
            if t is None:
                raise RuntimeError(
                    f"Executor.run: fetch variable {getattr(f, 'name', f)!r} "
                    f"is not computed by this program")
            out.append(np.asarray(t._value) if numpy else t)
        return out

    # -- inference replay (whole op list as ONE jitted program) ----------
    def _run_jitted(self, program, feed, fetch_list):
        feed_names = sorted(program._feed_vars)
        for name in feed_names:
            if name not in feed:
                raise KeyError(
                    f"Executor.run: feed is missing '{name}'")
        feed_vals = []
        for name in feed_names:
            v = feed[name]
            feed_vals.append(v._value if isinstance(v, Tensor)
                             else jnp.asarray(v))

        # externals: var refs read before produced and not feeds (e.g.
        # parameters) — passed as inputs each run so updates are visible.
        # The op-list walk is memoized per program version: serving loops
        # must not pay an O(num_ops) python pass per request.
        # caches live ON the Program so entries die with it — an executor-
        # held dict keyed by id(program) would grow unboundedly and could
        # replay a stale compiled op list after id reuse (advisor r2)
        _cache = program.__dict__.setdefault("_executor_cache", {})
        feed_ids = {id(program._feed_vars[n]) for n in feed_names}
        akey = (program.num_ops, tuple(sorted(feed_ids)))
        analysis = _cache.get(("analysis", akey))
        if analysis is None:
            produced = set(feed_ids)
            ext_ids = []
            ext_seen = set()
            for _, _, _, in_refs, out_ids in program._ops:
                for kind, ref in in_refs:
                    if kind == "v" and ref not in produced \
                            and ref not in ext_seen:
                        ext_seen.add(ref)
                        ext_ids.append(ref)
                produced.update(out_ids)
            analysis = (ext_ids, produced)
            _cache[("analysis", akey)] = analysis
        ext_ids, produced = analysis

        names_key = ("names", program.num_ops)
        name_map = _cache.get(names_key)
        if name_map is None:
            name_map = {}
            for t in program._tensors.values():
                n = getattr(t, "name", None)
                if n is not None and n not in name_map:
                    name_map[n] = t
            _cache[names_key] = name_map
        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, str):
                if f not in name_map:
                    raise RuntimeError(
                        f"Executor.run: no program variable named {f!r}")
                f = name_map[f]
            if not isinstance(f, Tensor):
                raise TypeError(
                    f"Executor.run: cannot fetch {f!r}")
            if id(f) not in produced and id(f) not in set(ext_ids):
                raise RuntimeError(
                    f"Executor.run: fetch variable "
                    f"{getattr(f, 'name', f)!r} is not computed by this "
                    f"program")
            fetch_ids.append(id(f))

        sig = (program.num_ops, tuple(fetch_ids),
               tuple((v.shape, str(v.dtype)) for v in feed_vals))
        fn = _cache.get(sig)
        if fn is None:
            ops = list(program._ops)
            f_ids = [id(program._feed_vars[n]) for n in feed_names]
            e_ids = list(ext_ids)
            out_ids_wanted = list(fetch_ids)

            def replay(feeds, exts):
                env = dict(zip(f_ids, feeds))
                env.update(zip(e_ids, exts))
                for _name, impl, statics, in_refs, out_ids in ops:
                    args = [env[r] if k == "v" else r for k, r in in_refs]
                    res = impl(*args, **statics)
                    res = res if isinstance(res, (tuple, list)) else (res,)
                    for oid, o in zip(out_ids, res):
                        env[oid] = o
                return [env[i] for i in out_ids_wanted]

            fn = jax.jit(replay)
            _cache[sig] = fn

        ext_vals = [program._tensors[i]._value for i in ext_ids]
        outs = fn(feed_vals, ext_vals)
        return [np.asarray(o) for o in outs]

    def close(self):
        pass


def name_scope(name):
    @contextlib.contextmanager
    def _ns():
        yield

    return _ns()
