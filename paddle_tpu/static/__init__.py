"""Static-graph compatibility surface (reference: python/paddle/static/).

The reference's Program/Executor stack (base/executor.py:1152,
framework.py:5736, StandaloneExecutor) interprets an op-list IR. On the TPU
stack the compiled artifact IS the program (jaxpr/StableHLO via jit), so
`static.Executor.run` executes traced callables; `paddle.enable_static()`
flips a flag that makes `data()` return placeholder specs consumed by a
traced build. This module provides the data-plumbing parity used by tests
and high-level training loops.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes

_static_mode = [False]


def _enable():
    _static_mode[0] = True


def _static_enabled():
    return _static_mode[0]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name, shape, dtype="float32", lod_level=0):
    shape = [1 if (s is None or (isinstance(s, int) and s < 0)) else s for s in shape]
    return InputSpec(shape, dtype, name)


class Program:
    def __init__(self):
        self._traced_fn = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        """In the TPU build, 'programs' are traced callables registered on
        the Program, or the caller uses eager/jit paths directly."""
        if fetch_list is None:
            return []
        out = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                out.append(f.numpy())
            elif callable(f):
                out.append(f(feed))
            else:
                out.append(f)
        return out

    def close(self):
        pass


def name_scope(name):
    import contextlib

    @contextlib.contextmanager
    def _ns():
        yield

    return _ns()
