"""Static-graph surface: op capture + replaying Executor.

Reference: python/paddle/static/ — Program (framework.py:5736) records ops
appended by the layer calls between `enable_static()` and `Executor.run`
(base/executor.py:1152), which then interprets the op list against a feed
dict and returns fetches.

TPU-native redesign: while static mode is on, every eager dispatch
(core/dispatch.py:_apply) ALSO appends (impl, statics, input-refs,
output-ids) to the default Program — the op-list IR is captured from the
same pure-jnp impls the eager mode runs, not from a separate operator
registry. `Executor.run` replays that list with the feed substituted:

- inference fetches replay as ONE jitted program (the whole captured op
  list traces into a single XLA executable, cached per feed signature);
- when `optimizer.minimize(loss)` was captured, run() replays eagerly
  through the autograd tape against the *live* parameter tensors, then
  backprops and steps — one exe.run == one training step, reference
  semantics (executor.py `run(main_program, feed, fetch_list)`).

Anything run() cannot honor (unknown fetch, missing feed) raises loudly —
never echoes the fetch list back.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core import dispatch as _dispatch

_static_mode = [False]
_capture_suspended = [0]


def _enable():
    _static_mode[0] = True
    _dispatch.set_static_capture_hook(_capture_op)


def _disable():
    _static_mode[0] = False
    _dispatch.set_static_capture_hook(None)


def _static_enabled():
    return _static_mode[0]


@contextlib.contextmanager
def _suspend_capture():
    _capture_suspended[0] += 1
    try:
        yield
    finally:
        _capture_suspended[0] -= 1


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


class Program:
    """Captured op-list program. `_ops` entries:
    (name, impl, statics, in_refs, out_ids) where in_refs are
    ('v', tensor_id) | ('c', raw_value)."""

    def __init__(self):
        self._ops = []
        self._tensors = {}        # tensor_id -> Tensor (live handles)
        self._feed_vars = {}      # name -> placeholder Tensor
        self._minimize = None     # (optimizer, loss Tensor)
        self._backward = None     # (loss Tensor, [(src, placeholder)])

    # -- capture --------------------------------------------------------
    def _record(self, name, impl, statics, tensor_args, outs):
        in_refs = []
        for t in tensor_args:
            if isinstance(t, Tensor):
                in_refs.append(("v", id(t)))
                self._tensors[id(t)] = t
            else:
                in_refs.append(("c", t))
        out_ids = []
        for o in outs:
            out_ids.append(id(o))
            self._tensors[id(o)] = o
        self._ops.append((name, impl, statics, in_refs, out_ids))

    def _register_minimize(self, optimizer, loss):
        self._minimize = (optimizer, loss)

    # -- reference API surface ------------------------------------------
    def global_block(self):
        return self

    def clone(self, for_test=False):
        if not for_test:
            return self
        p = Program()
        p._ops = list(self._ops)
        p._tensors = dict(self._tensors)
        p._feed_vars = dict(self._feed_vars)
        p._minimize = None  # the eval clone drops the training hook
        return p

    def list_vars(self):
        return list(self._tensors.values())

    @property
    def num_ops(self):
        return len(self._ops)


_default_main = [Program()]
_default_startup = [Program()]


def default_main_program():
    return _default_main[-1]


def default_startup_program():
    return _default_startup[-1]


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    """Reference: static.program_guard (framework.py:7436)."""
    _default_main.append(main_program)
    _default_startup.append(startup_program or Program())
    try:
        yield
    finally:
        _default_main.pop()
        _default_startup.pop()


def _capture_op(name, impl, statics, tensor_args, outs):
    if not _static_mode[0] or _capture_suspended[0]:
        return
    default_main_program()._record(name, impl, statics, tensor_args, outs)


def data(name, shape, dtype="float32", lod_level=0):
    """Reference: static.data — a feed placeholder. The returned Tensor
    carries a zero value at build time (shape propagation runs through the
    real kernels); dynamic dims (None/-1) build at size 1 and re-jit per
    fed batch size."""
    shape = [1 if (s is None or (isinstance(s, int) and s < 0)) else s
             for s in shape]
    t = Tensor(jnp.zeros(shape, dtypes.convert_dtype(dtype)))
    t.name = name
    t.stop_gradient = True
    default_main_program()._feed_vars[name] = t
    default_main_program()._tensors[id(t)] = t
    return t


class Executor:
    """Reference: static.Executor (base/executor.py:1152)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        program = program or default_main_program()
        feed = feed or {}
        if fetch_list is None or not fetch_list:
            # startup-program run: parameters initialize eagerly on this
            # stack, so there is nothing to execute.
            if program._ops and feed:
                raise RuntimeError(
                    "Executor.run with feed but no fetch_list: pass the "
                    "variables to fetch")
            return []
        if not program._ops:
            raise NotImplementedError(
                "Executor.run: this Program captured no ops — build the "
                "graph between paddle.enable_static() and run(), or use "
                "the eager/jit path")
        if program._minimize is not None:
            return self._run_train(program, feed, fetch_list)
        if program._backward is not None:
            return self._run_backward(program, feed, fetch_list)
        return self._run_jitted(program, feed, fetch_list)

    def _run_backward(self, program, feed, fetch_list):
        """append_backward / gradients replay: run eagerly, backward the
        registered loss, publish grads into their placeholder vars so
        fetch_list can name them (reference: the backward ops
        append_backward inserts into the Program)."""
        loss_var, pairs = program._backward
        # feed vars whose gradients were requested must join the tape
        grad_srcs = {id(s) for s, _ in pairs}
        env = self._replay_eager(program, feed,
                                 requires_grad_ids=grad_srcs)
        loss_t = env.get(id(loss_var))
        if loss_t is None:
            raise RuntimeError(
                "Executor.run: append_backward loss is not produced by "
                "this program's ops")
        with _suspend_capture():
            loss_t.backward()
        for src_var, ph in pairs:
            live = env.get(id(src_var), src_var)
            g = live.grad
            ph._value = (g._value if g is not None
                         else jnp.zeros_like(live._value))
            env[id(ph)] = ph
            with _suspend_capture():
                live.clear_grad() if hasattr(live, "clear_grad") else None
        out = self._collect(program, env, fetch_list, numpy=False)
        return [np.asarray(o._value) if isinstance(o, Tensor) else o
                for o in out]

    # -- training replay (eager tape against live parameters) -----------
    def _run_train(self, program, feed, fetch_list):
        env = self._replay_eager(program, feed)
        out = self._collect(program, env, fetch_list, numpy=False)
        opt, loss_var = program._minimize
        loss_t = env.get(id(loss_var))
        if loss_t is None:
            raise RuntimeError(
                "Executor.run: minimize() loss is not produced by this "
                "program's ops")
        with _suspend_capture():
            loss_t.backward()
            opt.step()
            opt.clear_grad()
        return [np.asarray(o._value) if isinstance(o, Tensor) else o
                for o in out]

    def _replay_eager(self, program, feed, requires_grad_ids=()):
        env = {}
        for name, ph in program._feed_vars.items():
            if name not in feed:
                raise KeyError(
                    f"Executor.run: feed is missing '{name}' "
                    f"(declared by static.data)")
            v = feed[name]
            v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            t = Tensor(v)
            t.stop_gradient = id(ph) not in requires_grad_ids
            env[id(ph)] = t
        with _suspend_capture():
            for op_name, impl, statics, in_refs, out_ids in program._ops:
                args = []
                for kind, ref in in_refs:
                    if kind == "c":
                        args.append(ref)
                    elif ref in env:
                        args.append(env[ref])
                    else:
                        args.append(program._tensors[ref])  # live external
                out = _dispatch.apply(op_name, impl, args, statics)
                outs = out if isinstance(out, tuple) else (out,)
                for oid, o in zip(out_ids, outs):
                    env[oid] = o
        return env

    def _collect(self, program, env, fetch_list, numpy=True):
        out = []
        for f in fetch_list:
            if isinstance(f, str):
                ph = program._feed_vars.get(f)
                named = [t for t in program._tensors.values()
                         if getattr(t, "name", None) == f]
                f = ph if ph is not None else (named[0] if named else None)
            if not isinstance(f, Tensor):
                raise TypeError(
                    f"Executor.run: cannot fetch {f!r} — fetch_list entries "
                    f"must be program variables")
            t = env.get(id(f), f if id(f) in program._tensors else None)
            if t is None:
                raise RuntimeError(
                    f"Executor.run: fetch variable {getattr(f, 'name', f)!r} "
                    f"is not computed by this program")
            out.append(np.asarray(t._value) if numpy else t)
        return out

    # -- inference replay (whole op list as ONE jitted program) ----------
    def _run_jitted(self, program, feed, fetch_list):
        feed_names = sorted(program._feed_vars)
        for name in feed_names:
            if name not in feed:
                raise KeyError(
                    f"Executor.run: feed is missing '{name}'")
        feed_vals = []
        for name in feed_names:
            v = feed[name]
            feed_vals.append(v._value if isinstance(v, Tensor)
                             else jnp.asarray(v))

        # externals: var refs read before produced and not feeds (e.g.
        # parameters) — passed as inputs each run so updates are visible.
        # The op-list walk is memoized per program version: serving loops
        # must not pay an O(num_ops) python pass per request.
        # caches live ON the Program so entries die with it — an executor-
        # held dict keyed by id(program) would grow unboundedly and could
        # replay a stale compiled op list after id reuse (advisor r2)
        _cache = program.__dict__.setdefault("_executor_cache", {})
        feed_ids = {id(program._feed_vars[n]) for n in feed_names}
        akey = (program.num_ops, tuple(sorted(feed_ids)))
        analysis = _cache.get(("analysis", akey))
        if analysis is None:
            produced = set(feed_ids)
            ext_ids = []
            ext_seen = set()
            for _, _, _, in_refs, out_ids in program._ops:
                for kind, ref in in_refs:
                    if kind == "v" and ref not in produced \
                            and ref not in ext_seen:
                        ext_seen.add(ref)
                        ext_ids.append(ref)
                produced.update(out_ids)
            analysis = (ext_ids, produced)
            _cache[("analysis", akey)] = analysis
        ext_ids, produced = analysis

        names_key = ("names", program.num_ops)
        name_map = _cache.get(names_key)
        if name_map is None:
            name_map = {}
            for t in program._tensors.values():
                n = getattr(t, "name", None)
                if n is not None and n not in name_map:
                    name_map[n] = t
            _cache[names_key] = name_map
        fetch_ids = []
        for f in fetch_list:
            if isinstance(f, str):
                if f not in name_map:
                    raise RuntimeError(
                        f"Executor.run: no program variable named {f!r}")
                f = name_map[f]
            if not isinstance(f, Tensor):
                raise TypeError(
                    f"Executor.run: cannot fetch {f!r}")
            if id(f) not in produced and id(f) not in set(ext_ids):
                raise RuntimeError(
                    f"Executor.run: fetch variable "
                    f"{getattr(f, 'name', f)!r} is not computed by this "
                    f"program")
            fetch_ids.append(id(f))

        sig = (program.num_ops, tuple(fetch_ids),
               tuple((v.shape, str(v.dtype)) for v in feed_vals))
        fn = _cache.get(sig)
        if fn is None:
            ops = list(program._ops)
            f_ids = [id(program._feed_vars[n]) for n in feed_names]
            e_ids = list(ext_ids)
            out_ids_wanted = list(fetch_ids)

            def replay(feeds, exts):
                env = dict(zip(f_ids, feeds))
                env.update(zip(e_ids, exts))
                for _name, impl, statics, in_refs, out_ids in ops:
                    args = [env[r] if k == "v" else r for k, r in in_refs]
                    res = impl(*args, **statics)
                    res = res if isinstance(res, (tuple, list)) else (res,)
                    for oid, o in zip(out_ids, res):
                        env[oid] = o
                return [env[i] for i in out_ids_wanted]

            fn = jax.jit(replay)
            _cache[sig] = fn

        ext_vals = [program._tensors[i]._value for i in ext_ids]
        outs = fn(feed_vals, ext_vals)
        return [np.asarray(o) for o in outs]

    def close(self):
        pass


def name_scope(name):
    @contextlib.contextmanager
    def _ns():
        yield

    return _ns()


# ---------------------------------------------------------------------------
# round-3 reference-surface completions (python/paddle/static/__init__.py)
# ---------------------------------------------------------------------------

Variable = Tensor  # reference: static.Variable is the graph-var handle


class BuildStrategy:
    """Reference: static.BuildStrategy — pass/fusion switches consumed by
    the C++ graph compiler. XLA owns those decisions here; the class keeps
    the config surface (attributes accepted, recorded, surfaced)."""

    def __init__(self):
        self.__dict__["_opts"] = {}

    def __setattr__(self, k, v):
        self._opts[k] = v

    def __getattr__(self, k):
        try:
            return self.__dict__["_opts"][k]
        except KeyError:
            raise AttributeError(k) from None

    def __repr__(self):
        return f"BuildStrategy({self._opts})"


class ExecutionStrategy:
    """Reference: static.ExecutionStrategy (thread counts / iteration
    drop) — the async interpreter knobs; recorded for parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Reference: static.CompiledProgram — wraps a Program with build
    options. Executor.run accepts it interchangeably (XLA compiles every
    replay, so 'compiled' is the default execution mode)."""

    def __init__(self, program, build_strategy=None):
        self._program = getattr(program, "_program", program)
        self._build_strategy = build_strategy

    def __getattr__(self, k):
        return getattr(self.__dict__["_program"], k)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Register backward on the current Program (reference:
    base/backward.py append_backward — inserts grad ops after `loss`).

    Returns [(param, grad_var)]; the grad vars become fetchable from
    Executor.run, which computes them by taping the replay."""
    prog = default_main_program()
    if parameter_list is None:
        # every live external with requires-grad reached by the ops
        seen, params = set(), []
        for _n, _i, _s, in_refs, out_ids in prog._ops:
            for kind, ref in in_refs:
                if kind != "v" or ref in seen:
                    continue
                seen.add(ref)
                t = prog._tensors.get(ref)
                if t is not None and not t.stop_gradient \
                        and ref not in {id(p) for p in
                                        prog._feed_vars.values()}:
                    params.append(t)
    else:
        params = list(parameter_list)
    if no_grad_set:
        drop = {id(v) for v in no_grad_set}
        params = [p for p in params if id(p) not in drop]
    pairs = []
    for p in params:
        ph = Tensor(jnp.zeros_like(p._value))
        ph.name = f"{getattr(p, 'name', 'param')}@GRAD"
        prog._tensors[id(ph)] = ph
        pairs.append((p, ph))
    prog._backward = (loss, pairs)
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference: static.gradients — grad vars of sum(targets) wrt inputs
    (feed vars or parameters)."""
    prog = default_main_program()
    loss = targets[0] if isinstance(targets, (list, tuple)) else targets
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    pairs = []
    for v in ins:
        ph = Tensor(jnp.zeros_like(v._value))
        ph.name = f"{getattr(v, 'name', 'var')}@GRAD"
        prog._tensors[id(ph)] = ph
        pairs.append((v, ph))
    prog._backward = (loss, pairs)
    return [ph for _, ph in pairs]


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..ops.extra import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..ops.extra import create_global_var as _cg
    return _cg(shape, value, dtype, persistable=persistable,
               force_cpu=force_cpu, name=name)


def cpu_places(device_count=None):
    """Reference: static.cpu_places."""
    from ..device import CPUPlace
    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Reference: static.cuda_places — accelerator places; on this runtime
    they are the jax devices."""
    import jax as _jax
    from ..device import CUDAPlace
    ids = device_ids if device_ids is not None \
        else range(len(_jax.devices()))
    return [CUDAPlace(i) for i in ids]


class _Scope:
    """Reference: global_scope() — name -> variable container."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        self._vars.setdefault(name, Tensor(jnp.zeros(())))
        return self._vars[name]

    def find_var(self, name):
        # also resolve names from the default program
        v = self._vars.get(name)
        if v is not None:
            return v
        for t in default_main_program()._tensors.values():
            if getattr(t, "name", None) == name:
                return t
        return None


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


import contextlib as _contextlib


@_contextlib.contextmanager
def device_guard(device=None):
    """Reference: static.device_guard — pins ops to a device in the
    Program. Single-device placement here is XLA's; the guard keeps the
    context-manager contract (validated name, no-op placement)."""
    if device is not None and not str(device).startswith(
            ("cpu", "gpu", "xpu", "npu", "tpu")):
        raise ValueError(f"device_guard: unknown device {device!r}")
    yield


def Print(input, first_n=-1, message=None, summarize=20, **kwargs):
    """Reference: static.Print op — logs tensor values when executed.
    Eager replay semantics: print now, pass the value through."""
    msg = f"{message or 'Print'}: " if message is not None else ""
    v = np.asarray(input._value if isinstance(input, Tensor) else input)
    flat = v.reshape(-1)[:summarize] if summarize and summarize > 0 else v
    print(f"{msg}shape={tuple(v.shape)} values={flat}")
    return input


def accuracy(input, label, k=1, correct=None, total=None):
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Reference: static.auc — batch AUC from predicted probabilities."""
    from ..metric import Auc
    m = Auc(num_thresholds=min(num_thresholds, 4095))
    m.update(np.asarray(input._value if isinstance(input, Tensor)
                        else input),
             np.asarray(label._value if isinstance(label, Tensor)
                        else label))
    return Tensor(jnp.asarray(np.float32(m.accumulate())))


class WeightNormParamAttr:
    """Reference: static.WeightNormParamAttr — ParamAttr requesting
    weight normalization (dim + the usual attr fields)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference:
    incubate/optimizer/modelaverage + static ExponentialMovingAverage):
    update() folds current params in; apply() swaps EMA values into the
    model (context manager), restore() puts the originals back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._ema = {}
        self._backup = {}
        self._step = 0

    def update(self, parameters=None):
        params = parameters if parameters is not None \
            else _collect_default_params()
        self._step += 1
        for p in params:
            key = id(p)
            v = np.asarray(p._value, np.float32)
            if key not in self._ema:
                self._ema[key] = (p, v.copy())
            else:
                _, e = self._ema[key]
                self._ema[key] = (p, self._decay * e
                                  + (1.0 - self._decay) * v)

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for key, (p, e) in self._ema.items():
            self._backup[key] = p._value
            # bias correction like the reference's thres_steps ramp
            corr = 1.0 - self._decay ** max(self._step, 1)
            p._value = jnp.asarray(e / corr, p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for key, (p, _e) in self._ema.items():
            if key in self._backup:
                p._value = self._backup.pop(key)


def _collect_default_params():
    prog = default_main_program()
    out = []
    for t in prog._tensors.values():
        if isinstance(t, Tensor) and not t.stop_gradient:
            out.append(t)
    return out


def load(program, model_path, executor=None, var_list=None):
    """Reference: static.load — restore persistables saved by
    static.save."""
    from ..framework_io import load as _load
    state = _load(model_path if model_path.endswith(".pdparams")
                  else model_path + ".pdparams")
    sd = state if isinstance(state, dict) else {}
    prog = getattr(program, "_program", program)
    by_name = {getattr(t, "name", None): t
               for t in prog._tensors.values() if isinstance(t, Tensor)}
    for k, v in sd.items():
        t = by_name.get(k)
        if t is not None:
            t._value = jnp.asarray(v.numpy() if isinstance(v, Tensor)
                                   else v)


def save(program, model_path):
    """Reference: static.save — persist program persistables."""
    from ..framework_io import save as _save
    prog = getattr(program, "_program", program)
    sd = {}
    for t in prog._tensors.values():
        if isinstance(t, Tensor) and not t.stop_gradient \
                and getattr(t, "name", None):
            sd[t.name] = t
    _save(sd, model_path if model_path.endswith(".pdparams")
          else model_path + ".pdparams")


def load_from_file(path):
    """Reference: static.load_from_file — raw bytes of a saved program."""
    with open(path, "rb") as f:
        return f.read()


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def serialize_program(feed_vars, fetch_vars, **kwargs):
    """Reference: static.serialize_program — portable program bytes. The
    portable form here is the pickled op-free interface description (the
    executable body ships via jit.save's StableHLO artifact)."""
    import pickle
    prog = default_main_program()
    return pickle.dumps({
        "feeds": sorted(prog._feed_vars),
        "num_ops": prog.num_ops,
    })


def deserialize_program(data):
    import pickle
    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, **kwargs):
    import pickle
    prog = default_main_program()
    vals = {getattr(t, "name", f"v{i}"): np.asarray(t._value)
            for i, t in enumerate(prog._tensors.values())
            if isinstance(t, Tensor) and not t.stop_gradient}
    return pickle.dumps(vals)


def deserialize_persistables(program, data, executor=None):
    import pickle
    vals = pickle.loads(data)
    prog = getattr(program, "_program", program)
    by_name = {getattr(t, "name", None): t
               for t in prog._tensors.values() if isinstance(t, Tensor)}
    for k, v in vals.items():
        if k in by_name:
            by_name[k]._value = jnp.asarray(v)


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """Reference: static.ctr_metric_bundle — (auc, batch_auc, ...) for CTR
    jobs; here the live AUC plus positive/total counts."""
    a = auc(input, label)
    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    return a, a, Tensor(jnp.asarray(np.float32(lab.sum()))), \
        Tensor(jnp.asarray(np.float32(lab.size)))


def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError(
        "IPU support: this framework targets TPU via XLA; Graphcore IPU "
        "sharding has no equivalent here (reference gates it behind a "
        "WITH_IPU build the same way)")


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError(
            "IPU support is not provided in the TPU build (reference "
            "gates it behind WITH_IPU)")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "IPU support is not provided in the TPU build (reference "
            "gates it behind WITH_IPU)")


@_contextlib.contextmanager
def scope_guard(scope):
    """Reference: static.scope_guard — swap the global scope."""
    global _GLOBAL_SCOPE
    prev = _GLOBAL_SCOPE
    _GLOBAL_SCOPE = scope
    try:
        yield
    finally:
        _GLOBAL_SCOPE = prev


def xpu_places(device_ids=None):
    raise NotImplementedError(
        "XPU (Kunlun) support is not provided in the TPU build "
        "(reference gates it behind WITH_XPU)")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError(
        "IPU support is not provided in the TPU build (reference gates "
        "it behind WITH_IPU)")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: static.py_func — embed a python callable as an op. The
    eager replay executes python anyway, so this simply calls through and
    copies into `out`."""
    res = func(x if isinstance(x, (list, tuple)) else [x])
    res = res if isinstance(res, (list, tuple)) else [res]
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o, r in zip(outs, res):
        o._value = (r._value if isinstance(r, Tensor)
                    else jnp.asarray(np.asarray(r)))
    return out


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference: static.normalize_program — prune to the inference
    subgraph. The replay executor already dead-code-eliminates via fetch
    analysis, so normalization is the eval clone."""
    prog = getattr(program, "_program", program)
    return prog.clone(for_test=True)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: static.save_inference_model — persist the deployable
    program. Deployment artifact here = the jitted replay of the captured
    Program: persistables + interface manifest (the executable body is
    re-jitted at load, XLA being the compiler)."""
    import pickle
    prog = getattr(program, "_program", program) or default_main_program()
    feeds = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetches = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    manifest = {
        "feed_names": [getattr(v, "name", None) for v in feeds],
        "fetch_names": [getattr(v, "name", None) for v in fetches],
    }
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(pickle.dumps(manifest))
    save(prog, path_prefix)
    # keep live handles for same-process load_inference_model
    _INFERENCE_REGISTRY[path_prefix] = (prog, feeds, fetches)


_INFERENCE_REGISTRY: dict = {}


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference: static.load_inference_model -> (program, feed_names,
    fetch_vars). Same-process loads reuse the live captured Program;
    cross-process deployment goes through jit.save/inference.Predictor
    (the StableHLO artifact)."""
    import pickle
    with open(path_prefix + ".pdmodel", "rb") as f:
        manifest = pickle.loads(f.read())
    if path_prefix in _INFERENCE_REGISTRY:
        prog, feeds, fetches = _INFERENCE_REGISTRY[path_prefix]
        load(prog, path_prefix)
        return prog, manifest["feed_names"], fetches
    raise NotImplementedError(
        "load_inference_model across processes: use paddle_tpu.jit.save + "
        "paddle_tpu.inference.create_predictor (the StableHLO deployment "
        "artifact); the pickled Program manifest carries no executable "
        "body")


def set_program_state(program, state_dict):
    """Reference: static.set_program_state — assign persistable values."""
    prog = getattr(program, "_program", program)
    by_name = {getattr(t, "name", None): t
               for t in prog._tensors.values() if isinstance(t, Tensor)}
    for k, v in state_dict.items():
        if k in by_name:
            by_name[k]._value = jnp.asarray(
                v.numpy() if isinstance(v, Tensor) else np.asarray(v))


def load_program_state(model_path, var_list=None):
    """Reference: static.load_program_state — read saved persistables as
    a name->ndarray dict."""
    from ..framework_io import load as _load
    state = _load(model_path if model_path.endswith(".pdparams")
                  else model_path + ".pdparams")
    return {k: np.asarray(v.numpy() if isinstance(v, Tensor) else v)
            for k, v in state.items()}
