"""String-tensor ops (reference: paddle/phi/kernels/strings/ —
strings_empty, strings_lower_upper over StringTensor,
paddle/phi/core/string_tensor.h).

TPU design note: strings never touch the device — the reference keeps
StringTensor on host for CPU kernels too. Here a StringTensor is a thin
wrapper over a numpy object array; ops are vectorized host transforms used
by data pipelines (tokenizers feed int ids to the device)."""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "empty", "empty_like",
           "lower", "upper", "copy"]


class StringTensor:
    """Host-side tensor of variable-length UTF-8 strings
    (reference: paddle/phi/core/string_tensor.h:31)."""

    def __init__(self, data, name=None):
        if isinstance(data, StringTensor):
            data = data._data
        self._data = np.asarray(data, dtype=object)
        self.name = name or "string_tensor"

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        return out if isinstance(out, str) else StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        other = other._data if isinstance(other, StringTensor) else other
        return bool(np.all(self._data == other))

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"


def to_string_tensor(data, name=None):
    return StringTensor(data, name)


def empty(shape, name=None):
    """Uninitialized (empty-string) StringTensor of the given shape
    (reference: strings_empty_kernel.cc)."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x, name=None):
    return empty(StringTensor(x).shape)


def _map(x, fn):
    x = StringTensor(x)
    return StringTensor(np.vectorize(fn, otypes=[object])(x._data)
                        if x._data.size else x._data.copy())


def lower(x, use_utf8_encoding=False, name=None):
    """Elementwise lowercase (reference: strings_lower_upper_kernel.h;
    use_utf8_encoding selects full unicode folding — python str.lower is
    always unicode-aware, which is a superset)."""
    return _map(x, str.lower)


def upper(x, use_utf8_encoding=False, name=None):
    """Elementwise uppercase (reference: strings_lower_upper_kernel.h)."""
    return _map(x, str.upper)


def copy(x, name=None):
    """Deep copy (reference: strings_copy_kernel.h)."""
    return StringTensor(np.array(StringTensor(x)._data, dtype=object))
