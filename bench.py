"""Benchmark gate: flagship GPT (ERNIE-3.0-base-class) pretrain step
throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

The reference publishes no in-tree numbers (BASELINE.md) — `vs_baseline` is
measured against an MFU-derived NCCL/GPU-class target: the north-star asks
for >=40% MFU; we report our measured MFU fraction relative to that target
(vs_baseline = our_MFU / 0.40), so >1.0 beats the reference target.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


# Relay-specific transport-fault signatures only; a bare "INTERNAL" would
# also match deterministic XLA compiler errors and turn a fast failure into
# minutes of futile recompiles.
_TRANSIENT_MARKERS = ("response body closed", "read body", "remote_compile",
                      "Connection reset", "Connection refused", "UNAVAILABLE",
                      "DEADLINE_EXCEEDED", "Socket closed")


class _RetriesExhausted(RuntimeError):
    """Inner retry gave up — final, never re-retried by the outer guard."""


def _is_transient(err: Exception) -> bool:
    if isinstance(err, _RetriesExhausted):
        return False
    msg = str(err)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def _retry_transient(fn, attempts=6, label="bench"):
    """Run fn() with retry/backoff on transient PJRT-relay transport faults.
    fn must rebuild any donated-buffer state itself on each call (a failed
    dispatched step poisons donated engine buffers)."""
    last = None
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — classify then re-raise
            if not _is_transient(e):
                raise
            last = e
            if attempt + 1 < attempts:
                wait = min(2.0 * (attempt + 1), 10.0)
                print(f"{label}: transient relay error (attempt "
                      f"{attempt + 1}/{attempts}), retrying in {wait:.0f}s: "
                      f"{e}", file=sys.stderr)
                time.sleep(wait)
    raise _RetriesExhausted(
        f"{label}: relay still failing after {attempts} attempts") from last


def _measure_with_retry(make_engine, batch, steps, attempts=6,
                        label="bench"):
    """Warmup + timed loop. Each attempt rebuilds the engine (the compiled
    program stays cached; rebuild cost is parameter init). Host readback is
    the only reliable fence through the relay (block_until_ready can return
    at enqueue time), so we fence via float() on the final loss.
    `batch` is the tuple of train_batch arguments."""

    def attempt():
        eng = make_engine()
        float(eng.train_batch(*batch))  # warmup/compile + readback fence
        t0 = time.perf_counter()
        loss = None
        for _ in range(steps):
            loss = eng.train_batch(*batch)
        final_loss = float(loss)  # device->host readback fences the chain
        dt = time.perf_counter() - t0
        return final_loss, dt

    return _retry_transient(attempt, attempts=attempts, label=label)


def _multistep_k(steps):
    """Steps-per-dispatch for the pipelined `Engine.train_batches` hot
    path: the largest divisor of `steps` at most BENCH_MULTISTEP
    (default 5). k=1 falls back to one dispatch per step."""
    ms = int(os.environ.get("BENCH_MULTISTEP", "5"))
    return max(i for i in range(1, max(1, min(ms, steps)) + 1)
               if steps % i == 0)


def _measure_multistep_with_retry(make_engine, batch, steps, k,
                                  label="bench"):
    """Warmup + timed loop over the fused k-step `train_batches` path
    (every micro-batch the same object -> the scan-invariant variant:
    zero per-step host work, docs/performance.md). The vision flagships
    ride this too now — ROADMAP item 1 lever (a): they dispatched per
    step while the gpt config measured +51% tok/s CPU from fusion
    alone."""

    def attempt():
        eng = make_engine()
        lv = eng.train_batches([batch] * k)   # warmup/compile fused step
        float(lv.numpy()[-1])                 # readback fence
        t0 = time.perf_counter()
        for _ in range(steps // k):
            lv = eng.train_batches([batch] * k)
        final_loss = float(lv.numpy()[-1])
        dt = time.perf_counter() - t0
        return final_loss, dt

    return _retry_transient(attempt, label=label)


def _export_profile(make_engine, batch, steps=3):
    """BENCH_PROFILE=1: capture host spans (engine dispatch / device_put /
    write-back plus eager op dispatches) over a few post-compile steps and
    export a chrome trace (path: BENCH_PROFILE_PATH, default
    bench_host_trace.json)."""
    prof = None
    try:
        from paddle_tpu.profiler import Profiler, ProfilerTarget

        eng = make_engine()
        float(eng.train_batch(*batch))  # compile outside the capture
        prof = Profiler(targets={ProfilerTarget.CPU})
        prof.start()
        try:
            for _ in range(steps):
                eng.train_batch(*batch)
                prof.step()
        finally:
            # a failed capture must not leave the tracer/profile hook live
            # — later benchmarks would silently pay tracing overhead
            prof.stop()
        path = os.environ.get("BENCH_PROFILE_PATH", "bench_host_trace.json")
        prof.export_chrome_tracing(path)
        prof.summary()
        print(f"bench: host chrome trace -> {path}", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 — profiling must never fail a bench
        print(f"bench: BENCH_PROFILE failed ({e})", file=sys.stderr)


def _emit(payload):
    # under BENCH_ALL the per-config lines go to stderr; the driver
    # contract (ONE json line on stdout) is satisfied by main() printing
    # the flagship payload last
    if os.environ.get("BENCH_ALL") == "1":
        print(json.dumps(payload), file=sys.stderr)
    else:
        print(json.dumps(payload))
    return payload


CONV_BASELINE_FILENAME = "CONV_BASELINE.json"


def _conv_objectives(row, on_tpu):
    """Declared ratchet objectives for one conv bench row. CPU smokes
    ratchet images/sec (generous slack: machine-to-machine variance);
    TPU rows ratchet the MFU itself — the number ROADMAP item 1 is
    actually about."""
    from paddle_tpu.obs.slo import Objective

    if on_tpu:
        return [Objective(
            f"{row}.tpu_mfu", "min",
            description=f"TPU train-step MFU of the {row} bench row",
            unit="mfu", slack=1.25)]
    return [Objective(
        f"{row}.cpu_images_per_sec", "min",
        description=f"CPU-smoke train images/sec of the {row} bench row",
        unit="img/s", slack=3.0)]


def _conv_gate(row, on_tpu, ips, mfu):
    """vs_baseline ratchet for the conv bench rows (ROADMAP item 1
    lever (c)), mirroring the BENCH_SLO gate shape: the measured row is
    evaluated against the checked-in CONV_BASELINE.json bound and a
    regression beyond the slack FAILS the bench like a correctness bug
    (e.g. the vision flagships silently falling off the multi-step scan
    path, or an NHWC relayout creeping back in). BENCH_CONV_WRITE=1
    re-ratchets THIS row's bound (merging — resnet50/ppyoloe/TPU rows
    ratchet independently). A platform with no ratcheted bound yet (no
    TPU conv rows exist) notes it and passes — the checked-in CPU
    bounds keep the gate real where measurement exists."""
    from paddle_tpu.obs import slo as slo_mod

    objectives = _conv_objectives(row, on_tpu)
    values = {o.name: (mfu if on_tpu else ips) for o in objectives}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        CONV_BASELINE_FILENAME)
    try:
        entries = slo_mod.load_baseline(path)
    except FileNotFoundError:
        entries = {}

    if os.environ.get("BENCH_CONV_WRITE") == "1":
        entries = slo_mod.write_baseline(
            path, values, objectives,
            note="conv bench ratchet bounds (ROADMAP item 1c); "
                 "re-ratchet one row with BENCH_CONV_WRITE=1 only "
                 "for an intentional, explained perf change",
            merge=entries)
        print(f"conv gate: ratcheted {[o.name for o in objectives]} -> "
              f"{path}", file=sys.stderr)

    missing = [o.name for o in objectives if o.name not in entries]
    if missing:
        print(f"conv gate: no ratcheted bound yet for {missing} on this "
              f"platform — BENCH_CONV_WRITE=1 ratchets one; gate skipped",
              file=sys.stderr)
        return True
    report = slo_mod.evaluate(values, entries, objectives)
    print(slo_mod.format_report(report), file=sys.stderr)
    return report["ok"]


def bench_resnet50(on_tpu, dev):
    """BASELINE config 1: ResNet-50 ImageNet-shape train step, images/sec."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import resnet50, resnet18

    batch = int(os.environ.get("BENCH_BATCH", "128" if on_tpu else "4"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "2"))
    size = 224 if on_tpu else 64
    # channels-last is the MXU-native conv layout on TPU: it removes the
    # relayout transposes XLA wraps around NCHW convs (measured ~2x MFU on
    # the train step). The CPU smoke now defaults NHWC too — ROADMAP
    # item 1 lever (b): graphcheck GC003 proves the NHWC conv region
    # transpose-free (graph_audit engine smoke + the planted-NCHW test),
    # so the smoke exercises the layout the TPU rows ship with;
    # BENCH_RESNET_FORMAT=NCHW measures the parity layout
    fmt = os.environ.get("BENCH_RESNET_FORMAT", "NHWC")
    model_fn, train_flops_img = (
        (resnet50, 3 * 4.1e9) if on_tpu else (resnet18, 3 * 1.8e9))

    def loss_fn(m, x, y):
        return paddle.nn.functional.cross_entropy(m(x), y).mean()

    def make_engine():
        paddle.seed(0)
        model = model_fn(num_classes=1000, data_format=fmt)
        opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                        parameters=model.parameters())
        mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
        return dist.parallelize(model, opt, loss_fn=loss_fn, mesh=mesh,
                                compute_dtype="bfloat16" if on_tpu else None)

    rng = np.random.RandomState(0)
    img_shape = (batch, 3, size, size) if fmt == "NCHW" \
        else (batch, size, size, 3)
    x = paddle.to_tensor(rng.randn(*img_shape).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype("int64"))

    k = _multistep_k(steps)
    if k > 1:
        final_loss, dt = _measure_multistep_with_retry(
            make_engine, (x, y), steps, k, label="resnet bench")
    else:
        final_loss, dt = _measure_with_retry(make_engine, (x, y), steps,
                                             label="resnet bench")
    ips = batch * steps / dt
    peak = 197e12 if on_tpu else float("inf")
    mfu = ips * train_flops_img / peak
    payload = _emit({
        "metric": f"resnet50 train images/sec ({size}px, bs={batch}, "
                  f"{fmt}, bf16)",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4) if on_tpu else 0.0,
        "extra": {"mfu": round(mfu, 4), "loss": round(final_loss, 4),
                  "steps_per_dispatch": k,
                  "platform": dev.platform},
    })
    return payload if _conv_gate("resnet50", on_tpu, ips, mfu) else None


def bench_bert_finetune(on_tpu, dev):
    """BASELINE config 2: BERT-base sequence-classification fine-tune step
    (AMP-O2-equivalent bf16 compute), sequences/sec."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models.bert import (
        bert_for_sequence_classification, BertConfig, CONFIGS,
    )

    mode = os.environ.get("BENCH_MODEL", "")
    if mode in CONFIGS:
        name = mode
    else:
        name = "bert_base" if on_tpu else "bert_tiny"
    seq = int(os.environ.get("BENCH_SEQLEN", "128"))
    batch = int(os.environ.get("BENCH_BATCH", "128" if on_tpu else "4"))
    steps = int(os.environ.get("BENCH_STEPS", "30" if on_tpu else "2"))

    def loss_fn(m, ids, labels):
        return paddle.nn.functional.cross_entropy(m(ids), labels).mean()

    def make_engine():
        paddle.seed(0)
        model = bert_for_sequence_classification(name, num_labels=2)
        opt = paddle.optimizer.AdamW(learning_rate=2e-5,
                                     parameters=model.parameters())
        mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
        return dist.parallelize(model, opt, loss_fn=loss_fn, mesh=mesh,
                                compute_dtype="bfloat16" if on_tpu else None)

    rng = np.random.RandomState(0)
    vocab = BertConfig(**CONFIGS[name]).vocab_size
    ids = paddle.to_tensor(
        rng.randint(0, vocab, (batch, seq)).astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, 2, (batch,)).astype("int64"))

    final_loss, dt = _measure_with_retry(make_engine, (ids, labels), steps,
                                         label="bert bench")
    sps = batch * steps / dt
    # fwd+bwd ~ 6*N FLOPs/token over MATMUL-BEARING params only: the
    # embedding tables are gathers with no matmul (no tied LM head in a
    # classification fine-tune), so they must not inflate MFU
    bc = BertConfig(**CONFIGS[name])
    h, i, L = bc.hidden_size, bc.intermediate_size, bc.num_hidden_layers
    n_matmul = L * (4 * h * h + 2 * h * i) + h * h  # blocks + pooler
    flops_seq = 6.0 * n_matmul * seq
    peak = 197e12 if on_tpu else float("inf")
    mfu = sps * flops_seq / peak
    return _emit({
        "metric": f"{name} fine-tune sequences/sec (seq={seq}, bs={batch}, "
                  f"bf16)",
        "value": round(sps, 2),
        "unit": "sequences/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4) if on_tpu else 0.0,
        "extra": {"mfu": round(mfu, 4), "loss": round(final_loss, 4),
                  "tokens_per_sec": round(sps * seq, 1),
                  "platform": dev.platform},
    })


def bench_ppyoloe(on_tpu, dev):
    """BASELINE config 3: PP-YOLOE-s-class anchor-free detector train step
    (COCO-shape synthetic), images/sec. Train FLOPs/img come from XLA's own
    cost analysis of the compiled forward (3x fwd for fwd+bwd), so the MFU
    is accounted against the model actually run, not a paper number."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.vision.models import ppyoloe_s

    batch = int(os.environ.get("BENCH_BATCH", "16" if on_tpu else "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "2"))
    size = 640 if on_tpu else 128
    max_boxes = 16
    # channels-last is the MXU-native conv layout (same lever as the
    # resnet config; NCHW<->NHWC loss parity is tested in-tree). CPU
    # smoke defaults NHWC too — ROADMAP item 1 lever (b), GC003-proven
    # transpose-free; BENCH_YOLO_FORMAT=NCHW measures the parity layout
    fmt = os.environ.get("BENCH_YOLO_FORMAT", "NHWC")

    def loss_fn(m, img, gb, gl, gm):
        return m.loss(img, gb, gl, gm)

    def make_engine():
        paddle.seed(0)
        model = ppyoloe_s(num_classes=80, max_boxes=max_boxes,
                          data_format=fmt)
        opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                        parameters=model.parameters())
        mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
        return dist.parallelize(model, opt, loss_fn=loss_fn, mesh=mesh,
                                compute_dtype="bfloat16" if on_tpu else None)

    rng = np.random.RandomState(0)
    img_shape = (batch, 3, size, size) if fmt == "NCHW" \
        else (batch, size, size, 3)
    img = paddle.to_tensor(rng.randn(*img_shape).astype("float32"))
    # synthetic boxes: xyxy within the image, ~8 valid per sample
    x0 = rng.uniform(0, size * 0.6, (batch, max_boxes, 2))
    wh = rng.uniform(size * 0.05, size * 0.35, (batch, max_boxes, 2))
    gb = paddle.to_tensor(
        np.concatenate([x0, np.minimum(x0 + wh, size - 1)], -1)
        .astype("float32"))
    gl = paddle.to_tensor(rng.randint(0, 80, (batch, max_boxes))
                          .astype("int64"))
    gm = paddle.to_tensor(
        (np.arange(max_boxes)[None] < 8).repeat(batch, 0)
        .astype("float32"))

    k = _multistep_k(steps)
    if k > 1:
        final_loss, dt = _measure_multistep_with_retry(
            make_engine, (img, gb, gl, gm), steps, k,
            label="ppyoloe bench")
    else:
        final_loss, dt = _measure_with_retry(
            make_engine, (img, gb, gl, gm), steps, label="ppyoloe bench")
    ips = batch * steps / dt

    # forward FLOPs of the model actually benched, from XLA cost analysis
    flops_img = None
    try:
        from paddle_tpu.distributed.engine import functionalize
        paddle.seed(0)
        from paddle_tpu.vision.models import ppyoloe_s as _mk
        m2 = _mk(num_classes=80, max_boxes=max_boxes, data_format=fmt)
        apply_fn, params, buffers = functionalize(
            m2, method=lambda *b: loss_fn(m2, *b))
        import jax.numpy as jnp
        pv = {n: p._value.astype("bfloat16" if on_tpu else "float32")
              if jnp.issubdtype(p._value.dtype, jnp.floating) else p._value
              for n, p in params.items()}
        bv = {n: b._value for n, b in buffers.items()}
        from paddle_tpu.core.tensor import Tensor as _T

        def fwd(p, b, i, g1, g2, g3):
            out, _ = apply_fn(p, b, _T(i), _T(g1), _T(g2), _T(g3))
            return out

        lowered = jax.jit(fwd).lower(
            pv, bv, img._value.astype("bfloat16" if on_tpu else "float32"),
            gb._value, gl._value, gm._value)
        from paddle_tpu.compat import cost_analysis

        cost = cost_analysis(lowered.compile())
        if cost and cost.get("flops"):
            flops_img = 3.0 * float(cost["flops"]) / batch
    except Exception as e:
        print(f"ppyoloe: cost analysis unavailable ({e})", file=sys.stderr)

    peak = 197e12 if on_tpu else float("inf")
    mfu = (ips * flops_img / peak) if flops_img else 0.0
    payload = _emit({
        "metric": f"ppyoloe_s detector train images/sec ({size}px, "
                  f"bs={batch}, {fmt}, bf16)",
        "value": round(ips, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(mfu / 0.40, 4) if (on_tpu and flops_img) else 0.0,
        "extra": {"mfu": round(mfu, 4), "loss": round(final_loss, 4),
                  "train_gflops_per_img": round(flops_img / 1e9, 2)
                  if flops_img else None,
                  "steps_per_dispatch": k,
                  "platform": dev.platform},
    })
    return payload if _conv_gate("ppyoloe", on_tpu, ips, mfu) else None


def bench_lora_decode(on_tpu, dev):
    """BASELINE config 5: LoRA-adapted LLM autoregressive decode tokens/sec.
    Decode is HBM-bandwidth-bound: the target is 40% of the
    bandwidth-implied ceiling (param_bytes/token over v5e's 819 GB/s)."""
    import jax
    import numpy as _np
    import paddle_tpu as paddle
    from paddle_tpu.models import gpt, generate, GenerationConfig
    from paddle_tpu.nn.lora import LoRAConfig, apply_lora

    name = os.environ.get("BENCH_MODEL",
                          "gpt3_1p3b" if on_tpu else "gpt_tiny")
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS",
                                    "128" if on_tpu else "8"))
    wdtype = os.environ.get("BENCH_WEIGHT_DTYPE", "")
    if wdtype and wdtype not in ("int8", "int4"):
        raise SystemExit(
            f"BENCH_WEIGHT_DTYPE={wdtype!r} unsupported (int8|int4)")
    kv_dtype = os.environ.get("BENCH_KV_DTYPE", "")
    if kv_dtype and kv_dtype != "int8":
        raise SystemExit(
            f"BENCH_KV_DTYPE={kv_dtype!r} unsupported (int8)")

    # Models whose f32 init exceeds HBM (llama2_7b: 27 GB on a 16 GB v5e)
    # must build + quantize on HOST, shipping only the quantized/bf16
    # buffers to the chip (the reference's deploy path likewise converts
    # offline and loads the quantized artifact).
    init_host = on_tpu and os.environ.get(
        "BENCH_INIT_HOST", "1" if name == "llama2_7b" else "0") == "1"
    import contextlib
    host_ctx = contextlib.nullcontext()
    if init_host:
        cpu0 = jax.local_devices(backend="cpu")[0]
        host_ctx = jax.default_device(cpu0)

    from paddle_tpu.nn.quant import quantize_for_inference, WeightOnlyLinear
    with host_ctx:
        paddle.seed(0)
        model = gpt(name)
        # adapters stay LIVE: the metric is LoRA-adapted decode (BASELINE
        # config 5), not base-model decode after a merge
        apply_lora(model, LoRAConfig(r=8))
        model.eval()
        if on_tpu:
            for _, p in model.named_parameters():
                p._value = p._value.astype("bfloat16")
        if kv_dtype:
            # int8 KV cache: halves the cache bytes (memory capability; the
            # measured throughput verdict is in docs/decode_perf.md)
            model.cache_quant = kv_dtype
        if wdtype:
            quantize_for_inference(model, weight_dtype=wdtype)
    if init_host:
        import jax.numpy as _jnp
        for _, p in model.named_parameters():
            v = p._value
            if _jnp.issubdtype(v.dtype, _jnp.floating):
                v = v.astype("bfloat16")
            p._value = jax.device_put(v, dev)
        for _, b in model.named_buffers():
            b._value = jax.device_put(b._value, dev)
    param_bytes = 0.0
    for _, sub in model.named_sublayers():
        if isinstance(sub, WeightOnlyLinear):
            param_bytes += float(_np.prod(sub.quant_weight.shape))  # 1B/el
    for n, p in model.named_parameters():
        param_bytes += float(_np.prod(p.shape)) * (2 if on_tpu else 4)

    rng = np.random.RandomState(0)
    prompt = paddle.to_tensor(rng.randint(0, 256, (batch, 16)).astype("int32"))
    cfg = GenerationConfig(max_new_tokens=new_tokens, do_sample=False,
                           use_cache=True)

    def attempt():
        out = generate(model, prompt, cfg)  # warmup/compile
        np.asarray(out.numpy())  # fence: async dispatch otherwise leaks
        best = float("inf")      # leftover work into the timed window
        for _ in range(3):
            t0 = time.perf_counter()
            out = generate(model, prompt, cfg)
            np.asarray(out.numpy())
            best = min(best, time.perf_counter() - t0)
        return best

    dt = _retry_transient(attempt, label="lora bench")
    tps = batch * new_tokens / dt
    bw_peak = 819e9
    bw_frac = (tps * param_bytes / batch) / bw_peak if on_tpu else 0.0
    return _emit({
        "metric": f"{name}+LoRA decode tokens/sec (bs={batch}, "
                  f"{new_tokens} new tokens, KV cache"
                  + (f", weight-only {wdtype}" if wdtype else "")
                  + (f", {kv_dtype} KV" if kv_dtype else "") + ")",
        "value": round(tps, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(bw_frac / 0.40, 4) if on_tpu else 0.0,
        "extra": {"bandwidth_frac": round(bw_frac, 4),
                  "platform": dev.platform},
    })


def bench_serving(on_tpu, dev):
    """BENCH_SERVING=1: dynamic-batching serving throughput. Requests/sec
    of a ServingPool over a small exported MLP at concurrency 1/8/32,
    batching off vs on (shape-bucketed AOT executables, docs/serving.md).
    Per-request outputs are checked bit-identical to sequential
    single-request execution; `vs_baseline` is the batched/unbatched
    speedup at the HIGHEST measured concurrency >= 8 (32 with the default
    sweep — where dispatch contention dominates and the win is stable;
    the acceptance gate is >= 1.5x). Every concurrency row is reported in
    `extra.rps`."""
    import concurrent.futures
    import itertools
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.inference import (
        BatchConfig, Config, ServingPool, create_predictor)

    n_req = int(os.environ.get("BENCH_SERVING_REQUESTS", "192"))
    conc = [int(c) for c in os.environ.get(
        "BENCH_SERVING_CONCURRENCY", "1,8,32").split(",")]
    pool_size = int(os.environ.get("BENCH_SERVING_POOL", "2"))
    wait_ms = float(os.environ.get("BENCH_SERVING_WAIT_MS", "3"))
    buckets = (1, 2, 4, 8, 16)

    with tempfile.TemporaryDirectory(prefix="bench-serving-") as workdir:
        os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE",
                              os.path.join(workdir, "compile-cache"))
        paddle.seed(0)
        # dispatch-bound on purpose: serving overhead (one XLA dispatch +
        # host round-trip per request) is what batching removes; compute
        # stays small so the CPU smoke measures the dispatch amortization
        # a TPU would see at much larger models
        model = nn.Sequential(nn.Linear(32, 32), nn.ReLU(),
                              nn.Linear(32, 32))
        model.eval()
        path = os.path.join(workdir, "infer")
        paddle.jit.save(model, path, input_spec=[
            paddle.to_tensor(np.zeros((1, 32), np.float32))])

        rng = np.random.RandomState(0)
        inputs = [rng.rand(1, 32).astype(np.float32) for _ in range(32)]
        ref = create_predictor(Config(path))
        want = [ref.run([x])[0] for x in inputs]

        def drive(pool, c):
            feeds = list(itertools.islice(itertools.cycle(
                range(len(inputs))), n_req))
            mismatches = [0]

            def one(i):
                out, = pool.infer([inputs[i]], timeout=30.0)
                if out.shape != want[i].shape or not (out == want[i]).all():
                    mismatches[0] += 1

            with concurrent.futures.ThreadPoolExecutor(max_workers=c) as ex:
                t0 = time.perf_counter()
                list(ex.map(one, feeds))
                dt = time.perf_counter() - t0
            return n_req / dt, mismatches[0]

        rows = {}
        dispatches = {}
        for mode in ("unbatched", "batched"):
            batching = BatchConfig(buckets=buckets, max_wait_ms=wait_ms) \
                if mode == "batched" else None
            pool = ServingPool(predictor=create_predictor(Config(path)),
                               size=pool_size, max_queue_depth=max(conc) * 4,
                               default_timeout=60.0, batching=batching)
            try:
                if batching is not None:
                    pool.warmup()
                drive(pool, 4)  # warm every member / executable
                for c in conc:
                    rps, bad = drive(pool, c)
                    rows[f"{mode}@{c}"] = round(rps, 1)
                    if bad:
                        rows[f"{mode}@{c}_MISMATCHES"] = bad
                if batching is not None:
                    bs = pool.stats()["batch"]
                    dispatches = {
                        "executed_by_bucket": bs["executed_by_bucket"],
                        "occupancy": round(bs["occupancy"], 3),
                        "requests": bs["requests"],
                        "padded": bs["padded_examples"],
                        "compile": bs["compile"],
                    }
            finally:
                pool.shutdown(drain_timeout=10.0)

        # gate at the highest measured concurrency (>= 8): that is where
        # per-request dispatch contention dominates and the batching win
        # is stable; lower-concurrency rows stay in `extra.rps`
        gate = max(c for c in conc if c >= 8) if any(
            c >= 8 for c in conc) else conc[-1]
        speedup = rows[f"batched@{gate}"] / rows[f"unbatched@{gate}"]
        return _emit({
            "metric": f"batched serving requests/sec (concurrency={gate}, "
                      f"pool={pool_size}, buckets={list(buckets)}, "
                      f"32x32 MLP)",
            "value": rows[f"batched@{gate}"],
            "unit": "requests/sec",
            "vs_baseline": round(speedup, 3),
            "extra": {"rps": rows, "batch": dispatches,
                      "requests_per_config": n_req,
                      "platform": dev.platform},
        })


def bench_slo(on_tpu, dev):
    """BENCH_SLO=1: the perf-SLO regression gate (docs/observability.md).

    Drives the CPU serving smoke (batched ServingPool over a small
    exported MLP at concurrency 8) with the obs metrics registry
    attached and a live HTTP exporter scraped mid-run, plus a tiny
    training loop, then evaluates the objectives declared in
    paddle_tpu.obs.slo (p99 request latency, throughput floor,
    queue-depth ceiling, steps/sec floor) against the checked-in
    SLO_BASELINE.json ratchet — exit nonzero on any breach, exactly how
    .tpu_lint_baseline.json gates lint. Generations are also streamed
    through a two-replica ServingRouter over stub decode engines, so
    the router's streaming overhead (time-to-first-token p99) rides the
    same gate. BENCH_SLO_WRITE=1 re-measures and rewrites the whole
    baseline (for an intentional, explained perf change);
    BENCH_SLO_WRITE=stream re-ratchets only the router_stream.* rows,
    merging over the existing bounds (slo.write_baseline(merge=)). The
    scrape is also verified: the pool's conservation law (admitted ==
    completed + failed + timed_out + cancelled) and the router's stream
    ledger must hold in the Prometheus text exposition itself."""
    import concurrent.futures
    import itertools
    import re
    import tempfile
    import urllib.request

    import paddle_tpu as paddle
    from paddle_tpu import nn, obs
    from paddle_tpu.obs import slo as slo_mod
    from paddle_tpu.inference import (
        BatchConfig, Config, LocalReplica, RouterConfig, ServingPool,
        ServingRouter, create_predictor)

    class _NullPredictor:
        """Pool-compatible stand-in: the streaming stage exercises the
        router's decode path only, never predictor compute."""

        def clone(self):
            return _NullPredictor()

        def reset_handles(self):
            pass

        def run(self, feeds):
            return [np.asarray(f) for f in feeds]

    class _StubStream:
        """Pump-contract stream over a precomputed token list: every
        token is available the instant the stream is placed, so the
        measured TTFT is pure router overhead."""

        def __init__(self, sid, toks):
            self.id, self.deadline, self.status = sid, None, "active"
            self._toks, self._i, self._end = toks, 0, None

        @property
        def tokens(self):
            return self._toks[:self._i]

        def cancel(self):
            if self._end is None:
                self._end = ("end", "cancelled", None)
                self.status = "cancelled"

        def poll(self, timeout=None):
            if self._end is not None:
                return self._end
            if self._i < len(self._toks):
                self._i += 1
                return ("tok", self._toks[self._i - 1])
            self._end = ("end", "completed", None)
            self.status = "completed"
            return self._end

    class _StubEngine:
        """Engine-duck-typed deterministic token recurrence — no XLA
        anywhere in the streaming hot path."""

        def __init__(self, generation):
            self._gen = int(generation)
            self._n = itertools.count()

        def submit(self, prompt_ids, max_new_tokens, timeout=None,
                   resume_committed=None):
            seq = ([int(t) for t in prompt_ids]
                   + [int(t) for t in (resume_committed or [])])
            toks = []
            for _ in range(int(max_new_tokens)):
                t = (sum(seq) * 31 + len(seq) + 7 * self._gen) % 211
                seq.append(t)
                toks.append(t)
            return _StubStream(f"s{next(self._n)}", toks)

        def shutdown(self, drain_timeout=None):
            pass

        def stats(self):
            return {}

    n_req = int(os.environ.get("BENCH_SLO_REQUESTS", "160"))
    conc = int(os.environ.get("BENCH_SLO_CONCURRENCY", "8"))
    pool_size = 2
    baseline_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        slo_mod.BASELINE_FILENAME)
    values = {}

    with tempfile.TemporaryDirectory(prefix="bench-slo-") as workdir:
        os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE",
                              os.path.join(workdir, "compile-cache"))
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(32, 32), nn.ReLU(),
                              nn.Linear(32, 32))
        model.eval()
        path = os.path.join(workdir, "infer")
        paddle.jit.save(model, path, input_spec=[
            paddle.to_tensor(np.zeros((1, 32), np.float32))])

        rng = np.random.RandomState(0)
        inputs = [rng.rand(1, 32).astype(np.float32) for _ in range(32)]

        reg = obs.MetricsRegistry()
        pool = ServingPool(predictor=create_predictor(Config(path)),
                           size=pool_size, max_queue_depth=conc * 8,
                           default_timeout=60.0,
                           batching=BatchConfig(max_wait_ms=2.0),
                           metrics=reg, name="slo")
        router = None
        try:
            server = pool.serve_metrics()
            pool.warmup()
            feeds = list(itertools.islice(
                itertools.cycle(range(len(inputs))), n_req))
            hist = reg.histogram("serving.request_seconds")
            with concurrent.futures.ThreadPoolExecutor(conc) as ex:
                # warm every member/executable outside the measure
                list(ex.map(lambda i: pool.infer([inputs[i]],
                                                 timeout=30.0),
                            feeds[:conc * 2]))
                # window the histogram too: the p99 objective must see
                # only the measured traffic, not the cold-start samples
                # the warm-up just absorbed (counts-delta quantile)
                warm_counts = hist.counts()
                t0 = time.perf_counter()
                list(ex.map(lambda i: pool.infer([inputs[i]],
                                                 timeout=30.0), feeds))
                dt = time.perf_counter() - t0

            snap = reg.snapshot()
            st = snap["collectors"]["serving.pool.slo"]
            window = [a - b for a, b in zip(hist.counts(), warm_counts)]
            values["serving_smoke.p99_latency_s"] = \
                hist.quantile(0.99, window)
            values["serving_smoke.throughput_rps"] = n_req / dt
            values["serving_smoke.queue_depth_peak"] = \
                st["queue_depth_peak"]

            # streaming TTFT through the distributed tier (docs/
            # serving.md): a two-replica ServingRouter over stub decode
            # engines shares the SAME registry, so its stream ledger
            # lands in the scrape below. Every token is ready the
            # moment a stream is placed — the p99 TTFT bound gates
            # ROUTER overhead (affinity pick, admission, first-frame
            # pump delivery), and a stall slipped into the pump loop
            # trips the gate even though model compute never moved.
            n_streams = int(os.environ.get("BENCH_SLO_STREAMS", "48"))
            router = ServingRouter(
                lambda rid, mdir, gen: LocalReplica(
                    rid, lambda d: _NullPredictor(), mdir, gen,
                    decode_factory=_StubEngine,
                    pool_kwargs=dict(default_timeout=30.0)),
                size=2,
                config=RouterConfig(default_timeout=30.0,
                                    affinity_block_tokens=4,
                                    no_capacity_wait=10.0),
                metrics=reg, name="slo")

            def stream_one(i):
                t0 = time.perf_counter()
                rs = router.submit_generate([i % 7, 1, 4, 1], 8,
                                            timeout=30.0)
                it = iter(rs)
                next(it)                    # first token lands
                ttft = time.perf_counter() - t0
                for _ in it:                # drain to completion
                    pass
                return ttft

            with concurrent.futures.ThreadPoolExecutor(4) as ex:
                list(ex.map(stream_one, range(8)))   # warm the tier
                ttfts = list(ex.map(stream_one, range(n_streams)))
            values["router_stream.ttft_p99_s"] = float(
                np.percentile(np.asarray(ttfts), 99))

            # the SAME registry must be scrapeable as Prometheus text
            # from the live endpoint, conservation law intact
            text = urllib.request.urlopen(
                server.url + "/metrics", timeout=10).read().decode()
            healthz = urllib.request.urlopen(
                server.url + "/healthz", timeout=10).status

            def scraped(field):
                m = re.search(
                    rf"^serving_pool_slo_{field} (\d+)$", text, re.M)
                if m is None:
                    raise RuntimeError(
                        f"serving_pool_slo_{field} missing from the "
                        f"scraped exposition")
                return int(m.group(1))

            balance = (scraped("completed") + scraped("failed")
                       + scraped("timed_out") + scraped("cancelled"))
            if scraped("admitted") != balance or healthz != 200:
                print(f"bench_slo: scraped conservation broken "
                      f"(admitted={scraped('admitted')} vs {balance}, "
                      f"healthz={healthz})", file=sys.stderr)
                return None

            # ... and so must the router's streams ledger (admitted ==
            # completed + failed + timed_out + cancelled + in_flight)
            rprefix = "serving_router_slo_streams_"
            ledger = {}
            for ln in text.splitlines():
                if ln.startswith(rprefix):
                    k, _, v = ln.partition(" ")
                    ledger[k[len(rprefix):]] = int(float(v))
            rbal = (ledger.get("completed", 0) + ledger.get("failed", 0)
                    + ledger.get("timed_out", 0)
                    + ledger.get("cancelled", 0)
                    + ledger.get("in_flight", 0))
            if ledger.get("admitted") != rbal \
                    or ledger.get("admitted", 0) < n_streams:
                print(f"bench_slo: scraped stream ledger broken "
                      f"({ledger})", file=sys.stderr)
                return None
            if "router_ttft_seconds" not in text:
                print("bench_slo: router_ttft_seconds missing from the "
                      "scraped exposition", file=sys.stderr)
                return None
        finally:
            if router is not None:
                router.shutdown(drain_timeout=10.0)
            pool.shutdown(drain_timeout=10.0)

    # training-dispatch floor: a tiny Engine loop (compile excluded)
    import jax
    import paddle_tpu.distributed as dist

    paddle.seed(0)
    tmodel = nn.Sequential(nn.Linear(16, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                    parameters=tmodel.parameters())
    mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
    eng = dist.parallelize(
        tmodel, opt, mesh=mesh,
        loss_fn=lambda m, x, y: paddle.nn.functional.mse_loss(m(x), y))
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(8, 16).astype("float32"))
    y = paddle.to_tensor(np.random.RandomState(1)
                         .rand(8, 1).astype("float32"))
    float(eng.train_batch(x, y).numpy())  # compile + fence
    steps = int(os.environ.get("BENCH_SLO_TRAIN_STEPS", "30"))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = eng.train_batch(x, y)
    float(loss.numpy())                   # readback fences the chain
    values["train_smoke.steps_per_sec"] = steps / (time.perf_counter()
                                                   - t0)

    gate_objectives = slo_mod.SERVING_SMOKE + slo_mod.ROUTER_STREAM
    write = os.environ.get("BENCH_SLO_WRITE", "")
    if write in ("1", "stream"):
        # "1" re-ratchets every row; "stream" re-ratchets only the
        # router_stream.* rows, carrying the rest of the checked-in
        # bounds over untouched (slo.write_baseline merge semantics)
        ratchet = gate_objectives if write == "1" \
            else slo_mod.ROUTER_STREAM
        try:
            merge = slo_mod.load_baseline(baseline_path)
        except FileNotFoundError:
            merge = None
        written = slo_mod.write_baseline(
            baseline_path, values, ratchet,
            note="CPU serving+stream+train smoke bounds; re-ratchet "
                 "with BENCH_SLO_WRITE=1 (all) or =stream "
                 "(router_stream.* only) for an intentional perf "
                 "change", merge=merge)
        print(f"bench_slo: wrote {len(written)} baseline bounds "
              f"({len(ratchet)} re-ratcheted) -> {baseline_path}",
              file=sys.stderr)

    baseline = slo_mod.load_baseline(baseline_path)
    report = slo_mod.evaluate(values, baseline, gate_objectives)
    print(slo_mod.format_report(report), file=sys.stderr)
    payload = _emit({
        "metric": f"SLO gate ({len(report['results'])} objectives, "
                  f"serving c={conc} n={n_req} + {n_streams} routed "
                  f"streams + {steps}-step train smoke)",
        "value": len(report["results"]) - len(report["breaches"]),
        "unit": "objectives passed",
        "vs_baseline": 1.0 if report["ok"] else 0.0,
        "extra": {"values": {k: round(v, 6) for k, v in values.items()},
                  "results": report["results"],
                  "platform": dev.platform},
    })
    return payload if report["ok"] else None


POD_BASELINE_FILENAME = "POD_BASELINE.json"


def _pod_objectives(on_tpu):
    """Declared objectives for the BENCH_POD gate. The CPU smoke mixes
    two DETERMINISTIC gates (dispatch count per step, per-chip param+opt
    state shrink — pure placement math, slack ~1) with a generous-slack
    throughput floor; TPU rows ratchet tokens/sec on the first hardware
    round, like the conv gate."""
    from paddle_tpu.obs.slo import Objective

    if on_tpu:
        return [Objective(
            "pod_smoke.tpu_fsdp_tokens_per_sec", "min",
            description="tokens/sec/chip of the fsdp-sharded GPT train "
                        "step on the real device mesh",
            unit="tok/s", slack=2.0)]
    return [
        Objective("pod_smoke.fsdp_tokens_per_sec", "min",
                  description="tokens/sec of the fsdp=8 GPT CPU-mesh "
                              "smoke (8 virtual devices, multi-step "
                              "scan path)",
                  unit="tok/s", slack=5.0),
        Objective("pod_smoke.dispatches_per_step", "max",
                  description="compiled-program dispatches per optimizer "
                              "step of the measured fsdp loop "
                              "(train_batches k-step scan: 1/k; "
                              "deterministic engine counter, not "
                              "wall-clock)",
                  unit="dispatches/step", slack=1.0),
        Objective("pod_smoke.fsdp_state_shrink", "min",
                  description="per-chip param+optimizer-state bytes, "
                              "dp-replicated / fsdp-sharded — the "
                              "fsdp-fits-where-dp-OOMs lever; "
                              "deterministic placement math "
                              "(graphcheck params_bytes_per_chip)",
                  unit="x", slack=1.1),
    ]


def bench_pod(on_tpu, dev):
    """BENCH_POD=1: pod-scale training defaults gate (ROADMAP item 3).

    Trains the GPT flagship config (gpt_tiny CPU smoke) through
    `MeshConfig(dp=8)` and `MeshConfig(fsdp=8)` engines on the
    8-virtual-device mesh and gates, via the checked-in POD_BASELINE.json
    ratchet (slo machinery, BENCH_POD_WRITE=1 re-ratchets):

    * loss parity dp vs fsdp <= 1e-5 at every step (hard gate — the
      in-graph gather/reduce-scatter must be semantically invisible);
    * dispatches/step of the measured loop (deterministic engine
      counter: the fsdp path must stay on the k-step scan hot path —
      dispatch/collective overlap is bought at dispatch granularity);
    * per-chip param+opt-state shrink dp/fsdp ~ N (deterministic
      placement math — the memory lever that makes 7B+ fit); the run
      also reports the "fits where dp OOMs" budget bracket;
    * fsdp tokens/sec floor (generous slack: CPU timing).
    """
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.analysis.graphcheck import params_bytes_per_chip
    from paddle_tpu.distributed import topology as topo_mod
    from paddle_tpu.models import gpt
    from paddle_tpu.obs import slo as slo_mod
    from paddle_tpu.sharding import MeshConfig

    n_dev = len(jax.devices())
    ways = int(os.environ.get("BENCH_POD_WAYS", "8"))
    if n_dev < ways:
        if on_tpu and n_dev >= 2:
            ways = n_dev
        else:
            print(f"bench_pod: needs {ways} devices, have {n_dev} "
                  f"(CPU smokes force 8 virtual devices via main()); "
                  f"gate skipped", file=sys.stderr)
            return {"metric": "pod gate (skipped: too few devices)",
                    "value": 0, "unit": "objectives passed",
                    "vs_baseline": 1.0, "extra": {"devices": n_dev}}

    name = "gpt_tiny" if not on_tpu else os.environ.get(
        "BENCH_MODEL", "gpt_base")
    seq = int(os.environ.get("BENCH_SEQLEN", "64" if not on_tpu else "1024"))
    batch = int(os.environ.get("BENCH_BATCH", str(ways)))
    steps = int(os.environ.get("BENCH_POD_STEPS", "8"))
    k = _multistep_k(steps)

    rng = np.random.RandomState(0)
    from paddle_tpu.models.gpt import CONFIGS

    vocab = CONFIGS[name]["vocab_size"]
    ids = paddle.to_tensor(
        rng.randint(0, vocab, (batch, seq)).astype("int32"))

    def make_engine(cfg):
        topo_mod.set_hybrid_communicate_group(None)
        paddle.seed(0)
        model = gpt(name, max_position_embeddings=max(
            seq, CONFIGS[name].get("max_position_embeddings", seq)))
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=model.parameters(),
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
        return dist.parallelize(
            model, opt, mesh=cfg,
            compute_dtype="bfloat16" if on_tpu else None)

    def run(cfg):
        def attempt():
            eng = make_engine(cfg)
            lv = eng.train_batches([(ids,)] * k)       # warmup/compile
            float(lv.numpy()[-1])
            d0, s0 = eng.stats["dispatches"], eng.stats["steps"]
            losses = []
            t0 = time.perf_counter()
            for _ in range(steps // k):
                lv = eng.train_batches([(ids,)] * k)
                losses.extend(float(x) for x in np.asarray(lv.numpy()))
            dt = time.perf_counter() - t0
            return (eng, losses, dt,
                    eng.stats["dispatches"] - d0, eng.stats["steps"] - s0)

        return _retry_transient(attempt, label="pod bench")

    def state_bytes(eng):
        # the same declared param+opt-state set the graphcheck
        # <site>::params watermark audits — one enumeration, one gate
        return params_bytes_per_chip(*eng.declared_state(), eng.mesh)

    fs_eng, fs_losses, fs_dt, fs_disp, fs_steps = run(MeshConfig(fsdp=ways))
    dp_eng, dp_losses, dp_dt, _d, _s = run(MeshConfig(dp=ways))

    # hard gate: the fsdp placement must be semantically invisible
    parity = max(abs(a - b) for a, b in zip(dp_losses, fs_losses))
    if parity > 1e-5:
        print(f"bench_pod: dp-vs-fsdp loss parity broken "
              f"(max |diff| {parity:.3e} > 1e-5)\n  dp   {dp_losses}\n"
              f"  fsdp {fs_losses}", file=sys.stderr)
        return None

    dp_bytes, fs_bytes = state_bytes(dp_eng), state_bytes(fs_eng)
    shrink = dp_bytes / max(fs_bytes, 1)
    # the fits-where-dp-OOMs bracket: any per-chip budget between the two
    # residencies admits the fsdp placement and rejects dp-replicated
    budget = (dp_bytes + fs_bytes) // 2
    tok_s = batch * seq * steps / fs_dt

    values = {}
    if on_tpu:
        values["pod_smoke.tpu_fsdp_tokens_per_sec"] = tok_s
    else:
        values["pod_smoke.fsdp_tokens_per_sec"] = tok_s
        values["pod_smoke.dispatches_per_step"] = fs_disp / max(fs_steps, 1)
        values["pod_smoke.fsdp_state_shrink"] = shrink

    objectives = _pod_objectives(on_tpu)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        POD_BASELINE_FILENAME)
    try:
        entries = slo_mod.load_baseline(path)
    except FileNotFoundError:
        entries = {}
    if os.environ.get("BENCH_POD_WRITE") == "1":
        entries = slo_mod.write_baseline(
            path, values, objectives,
            note="pod-scale fsdp training gate (ROADMAP item 3): CPU "
                 "deterministic dispatch/memory gates + throughput "
                 "floor; TPU rows ratchet on the first hardware round "
                 "with BENCH_POD_WRITE=1",
            merge=entries)
        print(f"bench_pod: ratcheted {sorted(values)} -> {path}",
              file=sys.stderr)

    missing = [o.name for o in objectives if o.name not in entries]
    extra = {
        "loss_parity_max_diff": parity,
        "dp_state_bytes_per_chip": int(dp_bytes),
        "fsdp_state_bytes_per_chip": int(fs_bytes),
        "fits_budget_bytes": int(budget),
        "dp_fits": bool(dp_bytes <= budget),
        "fsdp_fits": bool(fs_bytes <= budget),
        "steps_per_dispatch": k,
        "dp_tokens_per_sec": round(batch * seq * steps / dp_dt, 2),
        "mesh_ways": ways, "model": name, "seq": seq, "batch": batch,
        "platform": dev.platform,
    }
    if missing:
        print(f"bench_pod: no ratcheted bound yet for {missing} on this "
              f"platform — BENCH_POD_WRITE=1 ratchets; gate skipped",
              file=sys.stderr)
        report = {"ok": True, "results": [], "breaches": []}
    else:
        report = slo_mod.evaluate(values, entries, objectives)
        print(slo_mod.format_report(report), file=sys.stderr)
    payload = _emit({
        "metric": f"POD gate ({len(report['results'])} objectives, "
                  f"{name} dp vs fsdp x{ways}, {steps} steps)",
        "value": round(tok_s, 2),
        "unit": "tokens/sec (fsdp)",
        "vs_baseline": 1.0 if report["ok"] else 0.0,
        "extra": dict(extra,
                      values={n: round(v, 6) for n, v in values.items()},
                      results=report["results"]),
    })
    return payload if report["ok"] else None


def _bench_decode_shared_prefix(model, on_tpu):
    """BENCH_DECODE sub-row: copy-on-write prefix sharing. N sequences
    extend ONE system prompt; the sharing engine holds a single physical
    copy of the shared KV blocks (refcounts) and skips their prefill,
    multiplying admission headroom at a FIXED pool size. Outputs are
    checked bit-equal against unshared (prefix_cache=False) decode; the
    CPU-smoke gate is >= 1.5x admission headroom (peak blocks,
    deterministic block math) or >= 1.3x useful-tokens/sec."""
    import concurrent.futures

    from paddle_tpu.inference import DecodeEngine

    n_seq = int(os.environ.get("BENCH_DECODE_SHARED_SEQS", "8"))
    sys_len, sfx_len, max_new = 24, 8, 8
    vocab = model.cfg.vocab_size
    rng = np.random.RandomState(3)
    system = rng.randint(0, vocab, (sys_len,)).astype(np.int32)
    prompts = [np.concatenate([
        system, rng.randint(0, vocab, (sfx_len,)).astype(np.int32)])
        for _ in range(n_seq)]

    # a DELIBERATELY tight pool (15 allocatable blocks): each private
    # sequence reserves 5 worst-case blocks, so unshared decode can hold
    # ~3 residents — sharing cuts the FRESH reservation to 2 (the prefix
    # blocks exist once), so the same pool admits ~2x the residents.
    # That resident multiplier IS the admission headroom the gate
    # measures; with block math, it is deterministic on CPU.
    rows = {}
    outs = {}
    for mode, share in (("shared", True), ("unshared", False)):
        eng = DecodeEngine(
            model, max_length=48, block_size=8,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16, 32),
            prefill_chunk=8, prefix_cache=share, num_blocks=16,
            default_timeout=600.0)
        try:
            eng.warmup()
            eng.generate(system, 1)      # canary: seeds (or not) the cache
            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(n_seq) as ex:
                outs[mode] = list(ex.map(
                    lambda p: eng.generate(p, max_new), prompts))
            dt = time.perf_counter() - t0
            st = eng.stats()
            rows[mode] = {
                "useful_tokens_per_sec": round(n_seq * max_new / dt, 1),
                "peak_resident_seqs": st["peak_resident"],
                "peak_blocks": st["blocks"]["peak_allocated"],
                "prompt_tokens_reused": st["prefix_cache"]["tokens_reused"],
                "prefill_chunks": st["prefill_chunks"],
                "cow_copies": st["cow_copies"],
            }
        finally:
            eng.shutdown(drain_timeout=30.0)

    mismatches = sum(1 for a, b in zip(outs["shared"], outs["unshared"])
                     if a != b)
    total_prompt = n_seq * (sys_len + sfx_len) + sys_len
    headroom = rows["shared"]["peak_resident_seqs"] \
        / max(1, rows["unshared"]["peak_resident_seqs"])
    tps_ratio = (rows["shared"]["useful_tokens_per_sec"]
                 / max(1e-9, rows["unshared"]["useful_tokens_per_sec"]))
    return {
        "modes": rows,
        "sequences": n_seq,
        "mismatches": mismatches,
        "admission_headroom": round(headroom, 3),
        "tokens_per_sec_ratio": round(tps_ratio, 3),
        "prefill_frac_avoided": round(
            rows["shared"]["prompt_tokens_reused"] / total_prompt, 3),
    }


def _bench_decode_chunked_ttft(model, on_tpu):
    """BENCH_DECODE sub-row: chunked prefill vs monolithic on a
    long-prompt mixed workload. A 96-token prompt lands in a live engine
    followed immediately by short prompts: monolithic prefill stalls
    them for one giant dispatch; chunking (+ shortest-remaining-first
    prefill scheduling) lets the shorts' prefills and the running
    batch's decode steps interleave between chunks. Gate: measured
    TTFT-p99 improvement for the short sequences."""
    import concurrent.futures

    from paddle_tpu.inference import DecodeEngine

    n_short = int(os.environ.get("BENCH_DECODE_TTFT_SHORTS", "6"))
    long_len, short_len = 192, 6
    vocab = model.cfg.vocab_size
    rng = np.random.RandomState(5)
    long_prompt = rng.randint(0, vocab, (long_len,)).astype(np.int32)
    shorts = [rng.randint(0, vocab, (short_len,)).astype(np.int32)
              for _ in range(n_short)]

    rows = {}
    outs = {}
    for mode, chunk in (("chunked", 16), ("monolithic", False)):
        eng = DecodeEngine(
            model, max_length=256, block_size=8,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16, 192),
            prefill_chunk=chunk, prefix_cache=False, num_blocks=65,
            default_timeout=600.0)
        ttfts = []
        try:
            eng.warmup()
            # a running batch the long prefill would stall
            bg = [eng.submit(shorts[0], 32), eng.submit(shorts[1], 32)]
            for s in bg:
                next(iter(s))

            def one_short(p):
                t0 = time.perf_counter()
                s = eng.submit(p, 4)
                first = next(iter(s))
                ttfts.append(time.perf_counter() - t0)
                return [first] + [t for t in s]

            long_s = eng.submit(long_prompt, 4)
            # land the shorts while the long prefill is IN FLIGHT (the
            # head-of-line scenario): wait for its admission, then one
            # beat for the scheduler to dispatch its (first or only)
            # prefill
            deadline = time.perf_counter() + 5.0
            while (eng.stats()["prefilling"] < 1
                   and time.perf_counter() < deadline):
                time.sleep(0.001)
            time.sleep(0.004)
            with concurrent.futures.ThreadPoolExecutor(n_short) as ex:
                outs[mode] = list(ex.map(one_short, shorts[2:]
                                         + shorts[:2]))
            outs[mode].append(long_s.result())
            for s in bg:
                s.result()
            rows[mode] = {
                "ttft_p50_ms": round(
                    float(np.percentile(ttfts, 50)) * 1e3, 1),
                "ttft_p99_ms": round(
                    float(np.percentile(ttfts, 99)) * 1e3, 1),
                "prefill_chunks": eng.stats()["prefill_chunks"],
            }
        finally:
            eng.shutdown(drain_timeout=30.0)

    mismatches = sum(1 for a, b in zip(outs["chunked"], outs["monolithic"])
                     if a != b)
    return {
        "modes": rows,
        "mismatches": mismatches,
        "ttft_p99_improvement": round(
            rows["monolithic"]["ttft_p99_ms"]
            / max(1e-9, rows["chunked"]["ttft_p99_ms"]), 3),
    }


def _bench_decode_speculative(on_tpu):
    """BENCH_DECODE sub-row: speculative decoding (draft-proposed,
    one-dispatch verified, docs/llm_serving.md). The workload is the
    real speculative setting built by construction instead of
    distillation (a bench cannot train a draft): the draft is a 2-layer
    model, the target is the SAME two layers plus extra residual blocks
    whose output projections are scaled near zero — so the draft
    approximates the target closely (high acceptance, like a distilled
    draft would) while the target costs ~4x the draft per forward. The
    measured delta is the speculative machinery alone: K+1 tokens
    committed per target dispatch instead of 1. Outputs are checked
    bit-equal to `speculate_k=0` greedy decode — the acceptance
    criterion — and the CPU-smoke gate is >= 1.3x tokens/sec (each mode
    timed best-of-2; the TPU row lands with BENCH_r06)."""
    import concurrent.futures

    import paddle_tpu as paddle
    from paddle_tpu.inference import DecodeEngine
    from paddle_tpu.models import gpt

    n_seq = int(os.environ.get("BENCH_DECODE_SPEC_SEQS", "6"))
    k = int(os.environ.get("BENCH_DECODE_SPEC_K", "8"))
    n_layers = 8
    tiny = dict(vocab_size=97, hidden_size=48, num_heads=4,
                num_kv_heads=2, rope=True, swiglu=True, rms_norm=True,
                max_position_embeddings=64, tie_word_embeddings=False)
    paddle.seed(7)
    target = gpt("gpt_tiny", num_layers=n_layers, **tiny)
    paddle.seed(7)
    draft = gpt("gpt_tiny", num_layers=2, **tiny)
    target.eval()
    draft.eval()
    tp = dict(target.named_parameters())
    for name, p in draft.named_parameters():
        p._value = tp[name]._value     # shared early stack + emb + head
    for name, p in target.named_parameters():
        if any(f"layers.{i}." in name for i in range(2, n_layers)) \
                and ("out_proj" in name or "down_proj" in name):
            p._value = p._value * 0.05  # extra blocks ~ identity

    lens = [24, 32, 40, 32]
    want = [lens[i % len(lens)] for i in range(n_seq)]
    rng = np.random.RandomState(9)
    prompts = [rng.randint(0, 97, (8,)).astype(np.int32)
               for _ in range(n_seq)]

    rows, outs = {}, {}
    for mode in ("speculative", "greedy"):
        eng = DecodeEngine(
            target, max_length=64, block_size=8,
            decode_buckets=(1, 2, 4), prefill_buckets=(8,),
            prefix_cache=False, default_timeout=600.0,
            draft_model=draft if mode == "speculative" else None,
            speculate_k=k if mode == "speculative" else 0)
        try:
            eng.warmup()
            best, out, st0 = float("inf"), None, None
            for i in range(2):         # best-of-2: CPU timing variance
                # counters below are reported as deltas over the FINAL
                # run (each run commits the identical greedy tokens, so
                # per-run dispatch counts are deterministic) while
                # tokens/sec uses the best run's time — without the
                # snapshot the published dispatch/rollback counts would
                # be two-run totals, 2x the workload's
                if i == 1:
                    st0 = eng.stats()
                t0 = time.perf_counter()
                with concurrent.futures.ThreadPoolExecutor(n_seq) as ex:
                    out = list(ex.map(
                        lambda i: eng.generate(prompts[i], want[i]),
                        range(n_seq)))
                best = min(best, time.perf_counter() - t0)
            outs[mode] = out
            st = eng.stats()
            sp, sp0 = st["speculative"], st0["speculative"]
            proposed = sp["proposed"] - sp0["proposed"]
            accepted = sp["accepted"] - sp0["accepted"]
            committed = sp["committed"] - sp0["committed"]
            verifies = sp["verify_dispatches"] - sp0["verify_dispatches"]
            rows[mode] = {
                "tokens_per_sec": round(sum(want) / best, 1),
                "target_dispatches": (st["steps"] - st0["steps"])
                + verifies + (st["prefills"] - st0["prefills"]),
                "acceptance_rate": round(accepted / proposed, 3)
                if proposed else 0.0,
                "accepted_per_dispatch": round(committed / verifies, 2)
                if verifies else 0.0,
                "rolled_back": sp["rejected"] - sp0["rejected"],
                "fallback_rounds": sp["fallbacks"] - sp0["fallbacks"],
            }
        finally:
            eng.shutdown(drain_timeout=30.0)

    mismatches = sum(1 for a, b in zip(outs["speculative"],
                                       outs["greedy"]) if a != b)
    ratio = (rows["speculative"]["tokens_per_sec"]
             / max(1e-9, rows["greedy"]["tokens_per_sec"]))
    return {
        "modes": rows,
        "k": k,
        "sequences": n_seq,
        "target_layers": n_layers,
        "draft_layers": 2,
        "mismatches": mismatches,
        "tokens_per_sec_ratio": round(ratio, 3),
    }


def _bench_decode_multi_tenant(model, on_tpu):
    """BENCH_DECODE sub-row: multi-tenant LoRA decode (S-LoRA/Punica
    shape, docs/llm_serving.md). One resident base model serves many
    adapters; the batched mode decodes a MIXED-adapter batch through the
    one bucketed step executable (per-sequence adapter ids gather the
    slot-stacked A/B pages in-graph), while the baseline emulates
    single-tenant serving: one adapter's requests at a time, sequential
    waves. Both modes run the identical engine machinery and adapters,
    so the measured delta is adapter multiplexing alone. Per-request
    outputs are checked bit-identical across modes (greedy decode); the
    CPU-smoke gate is >= 1.5x tokens/sec at concurrency 8."""
    import concurrent.futures

    from paddle_tpu.inference import AdapterPool, DecodeEngine

    conc = int(os.environ.get("BENCH_DECODE_MT_SEQS", "8"))
    n_adapters = int(os.environ.get("BENCH_DECODE_MT_ADAPTERS", "8"))
    max_new = 16
    vocab = model.cfg.vocab_size
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, vocab, (6,)).astype(np.int32)
               for _ in range(conc)]
    names = [f"tenant-{i}" for i in range(n_adapters)]
    who = [names[i % n_adapters] for i in range(conc)]

    pool = AdapterPool(model, rank=4, slots=n_adapters + 1)
    weights = {}
    for i, nm in enumerate(names):
        w = {}
        for lname, (a, b) in pool.stacks().items():
            r = np.random.RandomState(100 + i)
            w[lname] = (r.normal(0, 0.05, a.shape[1:]).astype(np.float32),
                        r.normal(0, 0.05, b.shape[1:]).astype(np.float32))
        weights[nm] = w
    for nm in names:
        pool.load(nm, weights[nm])

    eng = DecodeEngine(
        model, max_length=32, block_size=8,
        decode_buckets=tuple(sorted({1, 2, 4, conc})),
        prefill_buckets=(8,), prefix_cache=False,
        default_timeout=600.0, adapters=pool,
        num_blocks=1 + 2 * conc * 4)
    rows, outs = {}, {}
    try:
        eng.warmup()
        for mode in ("sequential", "batched"):
            best, out = float("inf"), None
            for _ in range(2):        # best-of-2: CPU timing variance
                out = [None] * conc
                st0 = eng.stats()
                t0 = time.perf_counter()

                def one(i):
                    out[i] = eng.generate(prompts[i], max_new,
                                          adapter=who[i])

                if mode == "batched":
                    with concurrent.futures.ThreadPoolExecutor(conc) as ex:
                        list(ex.map(one, range(conc)))
                else:
                    # single-tenant emulation: swap the tenant's adapter
                    # in, serve its requests, next tenant — what a
                    # one-adapter-at-a-time deployment actually does
                    for nm in names:
                        pool.load(nm, weights[nm])
                        gang = [i for i in range(conc) if who[i] == nm]
                        with concurrent.futures.ThreadPoolExecutor(
                                len(gang)) as ex:
                            list(ex.map(one, gang))
                best = min(best, time.perf_counter() - t0)
                st = eng.stats()
            outs[mode] = out
            rows[mode] = {
                "tokens_per_sec": round(conc * max_new / best, 1),
                "steps": st["steps"] - st0["steps"],
            }
        astats = eng.stats()["adapters"]
        lookups = astats["hits"] + astats["misses"]
        rows["occupancy"] = round(astats["occupancy"], 3)
        rows["hit_rate"] = round(astats["hits"] / lookups, 3) \
            if lookups else 0.0
        rows["per_adapter"] = {nm: a["refs"]
                               for nm, a in astats["adapters"].items()}
    finally:
        eng.shutdown(drain_timeout=30.0)
    mismatches = sum(1 for a, b in zip(outs["batched"],
                                       outs["sequential"]) if a != b)
    ratio = (rows["batched"]["tokens_per_sec"]
             / max(1e-9, rows["sequential"]["tokens_per_sec"]))
    return {
        "modes": rows,
        "adapters": n_adapters,
        "sequences": conc,
        "mismatches": mismatches,
        "tokens_per_sec_ratio": round(ratio, 3),
    }


def _bench_decode_sampling_parity(model):
    """BENCH_DECODE sub-row: per-request sampling rides the batch as
    VALUES (inference/sampling.py), so a mixed-sampling workload must
    dispatch exactly like the all-greedy one — same step/prefill counts
    at every bucket, zero post-warmup compiles. This row asserts that
    dispatch-count parity instead of a speed gate (identical dispatches
    IS the perf claim: sampling adds no scheduler rounds and no
    retraces)."""
    import concurrent.futures

    from paddle_tpu.inference import DecodeEngine, SamplingParams

    conc = 8
    max_new = 12
    vocab = model.cfg.vocab_size
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, vocab, (6,)).astype(np.int32)
               for _ in range(conc)]
    mixes = [None,
             SamplingParams(temperature=0.8, seed=1),
             SamplingParams(temperature=1.2, top_k=8, seed=2),
             SamplingParams(temperature=0.7, top_p=0.9, seed=3),
             SamplingParams(temperature=0.0),
             SamplingParams(temperature=0.9, repetition_penalty=1.3,
                            seed=4),
             SamplingParams(temperature=1.0, top_k=4, top_p=0.95, seed=5),
             None]
    eng = DecodeEngine(
        model, max_length=32, block_size=8,
        decode_buckets=tuple(sorted({1, 2, 4, conc})),
        prefill_buckets=(8,), prefix_cache=False,
        default_timeout=600.0, num_blocks=1 + 2 * conc * 4)
    try:
        eng.warmup()
        counts = {}
        for mode in ("greedy", "mixed"):
            st0 = eng.stats()

            def one(i):
                sp = mixes[i] if mode == "mixed" else None
                return eng.generate(prompts[i], max_new, sampling=sp)

            with concurrent.futures.ThreadPoolExecutor(conc) as ex:
                list(ex.map(one, range(conc)))
            st = eng.stats()
            counts[mode] = {
                "steps": st["steps"] - st0["steps"],
                "prefills": st["prefills"] - st0["prefills"],
                "compiles": (st["compiles"]["built"]
                             - st0["compiles"]["built"]),
            }
    finally:
        eng.shutdown(drain_timeout=30.0)
    return {
        "modes": counts,
        "dispatch_parity": counts["greedy"]["steps"]
        == counts["mixed"]["steps"]
        and counts["greedy"]["prefills"] == counts["mixed"]["prefills"],
        "post_warmup_compiles": counts["mixed"]["compiles"]
        + counts["greedy"]["compiles"],
    }


def bench_decode(on_tpu, dev):
    """BENCH_DECODE=1: continuous-batching LLM decode — tokens/sec and
    p50/p99 time-to-first-token of the iteration-level `DecodeEngine`
    (inference/decode, docs/llm_serving.md) vs REQUEST-level batching on
    mixed-length generations.

    The baseline emulates what `DynamicBatcher` semantics give a
    generation workload: a formed batch decodes until its LONGEST member
    finishes (a batched program cannot stop per-row, so finished
    sequences keep occupying their slots doing padded work) and the next
    batch waits for the whole gang to drain. Both modes run the SAME
    paged, bucketed AOT step executables, so the measured delta is the
    scheduling policy alone — iteration-level join/leave vs
    head-of-line blocking. Only useful (per-request) tokens count toward
    tokens/sec; per-request outputs are checked identical across modes
    (greedy decode is deterministic). `vs_baseline` is the
    continuous/request-level tokens/sec ratio; the acceptance gate is
    >= 1.5x at concurrency >= 8. The CPU smoke runs a tiny varied-output
    GPT (rope + GQA + swiglu); real-model TPU numbers land in the next
    BENCH_r06.json."""
    import concurrent.futures
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.inference import DecodeEngine
    from paddle_tpu.models import gpt

    conc = int(os.environ.get("BENCH_DECODE_CONCURRENCY", "8"))
    lens = [int(x) for x in os.environ.get(
        "BENCH_DECODE_LENS", "3,4,6,8,10,12,16,20").split(",")]
    gangs = int(os.environ.get("BENCH_DECODE_GANGS", "6"))
    n_req = gangs * conc
    prompt_len = 6
    max_len = prompt_len + max(lens) + prompt_len  # headroom for prefill pad

    with tempfile.TemporaryDirectory(prefix="bench-decode-") as workdir:
        os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE",
                              os.path.join(workdir, "compile-cache"))
        paddle.seed(7)
        name = os.environ.get("BENCH_MODEL", "gpt_base" if on_tpu else "")
        if name:
            model = gpt(name, max_position_embeddings=max(max_len, 64))
        else:
            model = gpt("gpt_tiny", vocab_size=97, hidden_size=48,
                        num_heads=4, num_kv_heads=2, num_layers=2,
                        rope=True, swiglu=True, rms_norm=True,
                        max_position_embeddings=64,
                        tie_word_embeddings=False)
        model.eval()
        vocab = model.cfg.vocab_size
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, vocab, (prompt_len,)).astype(np.int32)
                   for _ in range(conc)]
        want = [lens[i % len(lens)] for i in range(n_req)]

        def make_engine():
            return DecodeEngine(
                model, max_length=max_len, block_size=8,
                decode_buckets=tuple(sorted({1, 2, 4, conc})),
                prefill_buckets=(8,), default_timeout=600.0,
                num_blocks=1 + 2 * conc * -(-max_len // 8))

        def percentiles(ts):
            return {"p50_ms": round(float(np.percentile(ts, 50)) * 1e3, 1),
                    "p99_ms": round(float(np.percentile(ts, 99)) * 1e3, 1)}

        results = {}
        for mode in ("request_level", "continuous"):
            eng = make_engine()
            try:
                eng.warmup()          # compiles excluded from the measure
                ttft = [0.0] * n_req
                outs = [None] * n_req
                t0 = time.perf_counter()

                def one(i, max_new):
                    s = eng.submit(prompts[i % conc], max_new)
                    toks = []
                    for tok in s:
                        if not toks:
                            ttft[i] = time.perf_counter() - t0
                        toks.append(tok)
                    outs[i] = toks

                if mode == "continuous":
                    # open admission: sequences join the running batch the
                    # moment a client thread frees up
                    with concurrent.futures.ThreadPoolExecutor(conc) as ex:
                        list(ex.map(one, range(n_req), want))
                else:
                    # request granularity: every gang member decodes to the
                    # gang max (the batched program can't stop per-row) and
                    # the next gang waits for a full drain
                    for g in range(0, n_req, conc):
                        gang = list(range(g, g + conc))
                        gmax = max(want[i] for i in gang)
                        with concurrent.futures.ThreadPoolExecutor(
                                conc) as ex:
                            list(ex.map(one, gang, [gmax] * conc))
                dt = time.perf_counter() - t0
                useful = sum(want)
                results[mode] = {
                    "tokens_per_sec": round(useful / dt, 1),
                    "ttft": percentiles(ttft),
                    "occupancy": round(eng.stats()["occupancy"], 3),
                    "steps": eng.stats()["steps"],
                }
                # useful tokens only: truncate gang overruns before compare
                results[mode]["outs"] = [o[:want[i]]
                                         for i, o in enumerate(outs)]
            finally:
                eng.shutdown(drain_timeout=30.0)

        mismatches = sum(
            1 for a, b in zip(results["continuous"].pop("outs"),
                              results["request_level"].pop("outs"))
            if a != b)
        speedup = (results["continuous"]["tokens_per_sec"]
                   / results["request_level"]["tokens_per_sec"])

        # Decode speed 2.0 rows: copy-on-write prefix sharing, chunked
        # prefill, and speculative decoding — each bit-equality-checked
        # against its private/monolithic/greedy twin and CPU-smoke
        # gated below
        shared = _bench_decode_shared_prefix(model, on_tpu)
        ttft = _bench_decode_chunked_ttft(model, on_tpu)
        spec = _bench_decode_speculative(on_tpu)
        mt = _bench_decode_multi_tenant(model, on_tpu)
        samp = _bench_decode_sampling_parity(model)

        payload = _emit({
            "metric": f"continuous-batching decode tokens/sec "
                      f"(concurrency={conc}, mixed max_new "
                      f"{min(lens)}..{max(lens)}, "
                      f"{name or 'tiny gpt'})",
            "value": results["continuous"]["tokens_per_sec"],
            "unit": "tokens/sec",
            "vs_baseline": round(speedup, 3),
            "extra": {"modes": results, "requests": n_req,
                      "mismatches": mismatches,
                      "shared_prefix": shared,
                      "chunked_prefill": ttft,
                      "speculative": spec,
                      "multi_tenant": mt,
                      "sampling_parity": samp,
                      "platform": dev.platform},
        })
        if mismatches:
            print(f"bench_decode: {mismatches} request(s) diverged between "
                  f"modes", file=sys.stderr)
            return None
        if conc >= 8 and speedup < 1.5:
            print(f"bench_decode: speedup {speedup:.2f}x below the 1.5x "
                  f"gate at concurrency {conc}", file=sys.stderr)
            return None
        if shared["mismatches"]:
            print(f"bench_decode: {shared['mismatches']} shared-prefix "
                  f"request(s) diverged from unshared decode",
                  file=sys.stderr)
            return None
        if shared["admission_headroom"] < 1.5 \
                and shared["tokens_per_sec_ratio"] < 1.3:
            print(f"bench_decode: prefix sharing gate failed — headroom "
                  f"{shared['admission_headroom']:.2f}x < 1.5x AND "
                  f"tokens/sec {shared['tokens_per_sec_ratio']:.2f}x "
                  f"< 1.3x", file=sys.stderr)
            return None
        if ttft["mismatches"]:
            print(f"bench_decode: {ttft['mismatches']} chunked-prefill "
                  f"request(s) diverged from monolithic decode",
                  file=sys.stderr)
            return None
        if ttft["ttft_p99_improvement"] < 1.1:
            print(f"bench_decode: chunked prefill gate failed — TTFT p99 "
                  f"improvement {ttft['ttft_p99_improvement']:.2f}x "
                  f"< 1.1x on the long-prompt mixed workload",
                  file=sys.stderr)
            return None
        if spec["mismatches"]:
            print(f"bench_decode: {spec['mismatches']} speculative "
                  f"request(s) diverged from speculate_k=0 greedy decode",
                  file=sys.stderr)
            return None
        if spec["tokens_per_sec_ratio"] < 1.3:
            print(f"bench_decode: speculative gate failed — "
                  f"{spec['tokens_per_sec_ratio']:.2f}x tokens/sec "
                  f"< 1.3x vs speculate_k=0 (acceptance "
                  f"{spec['modes']['speculative']['acceptance_rate']})",
                  file=sys.stderr)
            return None
        if mt["mismatches"]:
            print(f"bench_decode: {mt['mismatches']} multi-tenant "
                  f"request(s) diverged between batched mixed-adapter "
                  f"decode and sequential per-adapter serving",
                  file=sys.stderr)
            return None
        if mt["tokens_per_sec_ratio"] < 1.5:
            print(f"bench_decode: multi-tenant gate failed — "
                  f"{mt['tokens_per_sec_ratio']:.2f}x tokens/sec < 1.5x "
                  f"vs sequential per-adapter serving at concurrency "
                  f"{mt['sequences']}", file=sys.stderr)
            return None
        if not samp["dispatch_parity"] or samp["post_warmup_compiles"]:
            print(f"bench_decode: sampling parity gate failed — mixed-"
                  f"sampling dispatch counts {samp['modes']['mixed']} vs "
                  f"greedy {samp['modes']['greedy']} "
                  f"({samp['post_warmup_compiles']} post-warmup "
                  f"compiles)", file=sys.stderr)
            return None
        return payload


def bench_gpt(on_tpu, dev):
    """Flagship (BASELINE north star): GPT/ERNIE-base-class pretrain step."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import gpt
    from paddle_tpu.models.gpt import GPTConfig, CONFIGS, flops_per_token

    name = os.environ.get("BENCH_MODEL", "gpt_base")
    seq_len = int(os.environ.get("BENCH_SEQLEN", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "16" if on_tpu else "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    if not on_tpu:  # CPU smoke: shrink
        name = os.environ.get("BENCH_MODEL", "gpt_tiny")
        seq_len = min(seq_len, 128)

    cfg = GPTConfig(**{**CONFIGS[name],
                       "max_position_embeddings": max(
                           seq_len,
                           CONFIGS[name].get("max_position_embeddings",
                                             seq_len))})

    def make_engine():
        paddle.seed(0)
        model = gpt(name,
                    max_position_embeddings=cfg.max_position_embeddings)
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=model.parameters(),
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
        mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
        return dist.parallelize(model, opt, mesh=mesh,
                                compute_dtype="bfloat16" if on_tpu else None)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype("int32"))

    # The axon PJRT relay sporadically drops a response mid-read
    # ("INTERNAL ... response body closed"); these are transient transport
    # faults, not program errors — retry with backoff, rebuilding the engine
    # each attempt (donated buffers are poisoned by a failed step).
    #
    # BENCH_MULTISTEP=k (default 5) drives the pipelined hot path: k
    # optimizer steps per dispatch through Engine.train_batches' fused
    # lax.scan variant — no host work between micro-steps
    # (docs/performance.md). BENCH_MULTISTEP=1 restores one dispatch/step.
    ms = int(os.environ.get("BENCH_MULTISTEP", "5"))
    k = max(i for i in range(1, max(1, min(ms, steps)) + 1)
            if steps % i == 0)
    if k > 1:
        def attempt():
            eng = make_engine()
            lv = eng.train_batches([(ids,)] * k)  # warmup/compile fused k-step
            float(lv.numpy()[-1])                 # readback fence
            t0 = time.perf_counter()
            for _ in range(steps // k):
                lv = eng.train_batches([(ids,)] * k)
            final_loss = float(lv.numpy()[-1])
            dt = time.perf_counter() - t0
            return final_loss, dt

        final_loss, dt = _retry_transient(attempt)
    else:
        final_loss, dt = _measure_with_retry(make_engine, (ids,), steps)

    if os.environ.get("BENCH_PROFILE") == "1":
        _export_profile(make_engine, (ids,))

    tokens = batch * seq_len * steps
    tps = tokens / dt

    flops_tok = flops_per_token(cfg, seq_len)
    # v5e peak bf16: 197 TFLOP/s; CPU has no meaningful peak — report 0 MFU
    peak = 197e12 if on_tpu else float("inf")
    mfu = tps * flops_tok / peak
    vs_baseline = mfu / 0.40 if on_tpu else 0.0

    return {
        "metric": f"{name} pretrain tokens/sec/chip (seq={seq_len}, "
                  f"bs={batch}, bf16)",
        "value": round(tps, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {"mfu": round(mfu, 4), "loss": round(final_loss, 4),
                  "steps": steps, "steps_per_dispatch": k,
                  "platform": dev.platform},
    }


LONGCTX_BASELINE_FILENAME = "LONGCTX_BASELINE.json"


def _longctx_objectives(on_tpu):
    """Declared ratchet objectives for the long-context serving row:
    chunked-prefill TTFT must not grow, decode tokens/sec must not drop.
    CPU smoke bounds are generous (machine variance); TPU rows ratchet
    independently under their own names."""
    from paddle_tpu.obs.slo import Objective

    pre = "tpu" if on_tpu else "cpu"
    return [
        Objective(f"longctx.{pre}_ttft_ms", "max",
                  description="long-prompt CP chunked-prefill time to "
                              "first token",
                  unit="ms", slack=3.0),
        Objective(f"longctx.{pre}_tokens_per_sec", "min",
                  description="decode tokens/sec after a long-prompt CP "
                              "chunked prefill",
                  unit="tok/s", slack=3.0),
    ]


def _longctx_gate(on_tpu, ttft_ms, tps):
    """vs_baseline ratchet for BENCH_LONGCTX (mirrors the conv gate):
    evaluated against the checked-in LONGCTX_BASELINE.json bounds; a
    breach beyond the slack fails the bench like a correctness bug
    (e.g. the prefill chunks falling off the cp-sharded executable and
    recompiling, or the ring schedule degenerating to a serial gather).
    BENCH_LONGCTX_WRITE=1 re-ratchets this platform's rows (merging)."""
    from paddle_tpu.obs import slo as slo_mod

    objectives = _longctx_objectives(on_tpu)
    values = {objectives[0].name: ttft_ms, objectives[1].name: tps}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        LONGCTX_BASELINE_FILENAME)
    try:
        entries = slo_mod.load_baseline(path)
    except FileNotFoundError:
        entries = {}

    if os.environ.get("BENCH_LONGCTX_WRITE") == "1":
        entries = slo_mod.write_baseline(
            path, values, objectives,
            note="long-context serving ratchet bounds (ISSUE 19); "
                 "re-ratchet with BENCH_LONGCTX_WRITE=1 only for an "
                 "intentional, explained perf change",
            merge=entries)
        print(f"longctx gate: ratcheted {[o.name for o in objectives]} "
              f"-> {path}", file=sys.stderr)

    missing = [o.name for o in objectives if o.name not in entries]
    if missing:
        print(f"longctx gate: no ratcheted bound yet for {missing} on "
              f"this platform — BENCH_LONGCTX_WRITE=1 ratchets; gate "
              f"skipped", file=sys.stderr)
        return True
    report = slo_mod.evaluate(values, entries, objectives)
    print(slo_mod.format_report(report), file=sys.stderr)
    return report["ok"]


def bench_longctx(on_tpu, dev):
    """BENCH_LONGCTX=1: long-context serving row — TTFT and decode
    tokens/sec at long prompt lengths through the DecodeEngine's
    context-parallel chunked prefill (prefill token buffer sequence-
    sharded along the mesh `cp` axis; each absolute-boundary chunk is
    one ring-scheduled unit, docs/long_context.md). The CPU smoke runs
    the tiny rope/GQA/swiglu GPT on the 8-virtual-device mesh with
    MeshConfig(cp=4) and cross-checks the cp output bit-identical to
    the single-device engine; TPU rows ratchet under their own
    objective names. Gated against LONGCTX_BASELINE.json."""
    import tempfile

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.inference import DecodeEngine
    from paddle_tpu.models import gpt
    from paddle_tpu.sharding import MeshConfig

    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN",
                                    "3072" if on_tpu else "96"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS",
                                    "64" if on_tpu else "8"))
    cp = int(os.environ.get("BENCH_CP", "4"))
    chunk = int(os.environ.get("BENCH_PREFILL_CHUNK",
                               "512" if on_tpu else "32"))
    max_len = prompt_len + new_tokens + 8

    mesh = None
    if cp > 1 and jax.device_count() >= cp:
        mesh = MeshConfig(cp=cp).build()
    elif cp > 1:
        print(f"bench_longctx: {jax.device_count()} device(s) < cp={cp}; "
              f"running unsharded", file=sys.stderr)
        cp = 1

    with tempfile.TemporaryDirectory(prefix="bench-longctx-") as workdir:
        os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE",
                              os.path.join(workdir, "compile-cache"))

        def build_model():
            paddle.seed(7)
            name = os.environ.get("BENCH_MODEL",
                                  "gpt_base" if on_tpu else "")
            if name:
                m = gpt(name, max_position_embeddings=max(max_len, 64))
            else:
                m = gpt("gpt_tiny", vocab_size=97, hidden_size=48,
                        num_heads=4, num_kv_heads=2, num_layers=2,
                        rope=True, swiglu=True, rms_norm=True,
                        max_position_embeddings=max_len,
                        tie_word_embeddings=False)
            m.eval()
            return m

        model = build_model()
        vocab = model.cfg.vocab_size
        prompt = np.random.RandomState(0).randint(
            1, vocab - 1, (prompt_len,)).astype(np.int32)
        # the largest bucket admits the full prompt (max_prompt is bucket-
        # capped even when chunking); the chunk bucket does the work —
        # every dispatched chunk is `chunk` long, cp | chunk
        geo = dict(max_length=max_len, block_size=8,
                   decode_buckets=(1,),
                   prefill_buckets=tuple(sorted({chunk, prompt_len})),
                   prefill_chunk=chunk, default_timeout=600.0)

        bit_identical = None
        if not on_tpu:
            ref_eng = DecodeEngine(build_model(), **geo)
            try:
                ref_toks = ref_eng.generate(prompt, new_tokens,
                                            timeout=600.0)
            finally:
                ref_eng.shutdown()

        eng = DecodeEngine(model, **geo, mesh=mesh)
        try:
            eng.warmup()
            toks = eng.generate(prompt, new_tokens, timeout=600.0)
            if not on_tpu:
                bit_identical = (toks == ref_toks)

            def best_of(n, fn):
                best = float("inf")
                for _ in range(n):
                    t0 = time.perf_counter()
                    fn()
                    best = min(best, time.perf_counter() - t0)
                return best

            ttft_s = _retry_transient(
                lambda: best_of(3, lambda: eng.generate(
                    prompt, 1, timeout=600.0)),
                label="longctx ttft")
            full_s = _retry_transient(
                lambda: best_of(3, lambda: eng.generate(
                    prompt, new_tokens, timeout=600.0)),
                label="longctx decode")
        finally:
            eng.shutdown()

    ttft_ms = ttft_s * 1e3
    tps = new_tokens / full_s
    ok = _longctx_gate(on_tpu, ttft_ms, tps)
    if bit_identical is False:
        print("bench_longctx: CP output DIVERGED from single-device "
              "engine", file=sys.stderr)
        ok = False
    payload = _emit({
        "metric": f"long-context decode tokens/sec (prompt={prompt_len}, "
                  f"cp={cp}, chunked prefill x{-(-prompt_len // chunk)})",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": 0.0,
        "extra": {"ttft_ms": round(ttft_ms, 1), "prompt_len": prompt_len,
                  "new_tokens": new_tokens, "cp": cp,
                  "prefill_chunk": chunk,
                  "bit_identical_vs_single_device": bit_identical,
                  "platform": dev.platform},
    })
    return payload if ok else None


def main():
    if (os.environ.get("BENCH_POD") == "1"
            or os.environ.get("BENCH_LONGCTX") == "1") and \
            "tpu" not in os.environ.get("JAX_PLATFORMS", "").lower():
        # the pod gate's CPU smoke needs the 8-virtual-device mesh, and
        # the flag must land BEFORE jax initializes its backend
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    import jax

    # one-chip bench (the driver runs on a single real TPU chip)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" or "TPU" in str(dev.device_kind)

    if os.environ.get("BENCH_POD") == "1":
        # pod-scale training defaults gate: dp-vs-fsdp on the (virtual)
        # pod mesh against the checked-in POD_BASELINE.json ratchet
        return 0 if bench_pod(on_tpu, dev) else 1

    if os.environ.get("BENCH_SLO") == "1":
        # perf-SLO regression gate: declared objectives vs the checked-in
        # SLO_BASELINE.json ratchet; nonzero exit on breach
        return 0 if bench_slo(on_tpu, dev) else 1

    if os.environ.get("BENCH_SERVING") == "1":
        # serving-throughput mode: its own one-line JSON (requests/sec,
        # batched-vs-unbatched) instead of the flagship train metric
        return 0 if bench_serving(on_tpu, dev) else 1

    if os.environ.get("BENCH_DECODE") == "1":
        # continuous-batching decode mode: tokens/sec + TTFT, iteration-
        # level engine vs request-level batching (gate >= 1.5x at c >= 8)
        return 0 if bench_decode(on_tpu, dev) else 1

    if os.environ.get("BENCH_LONGCTX") == "1":
        # long-context serving: TTFT + tokens/sec at long prompt lengths
        # through the cp-sharded chunked prefill, ratcheted against the
        # checked-in LONGCTX_BASELINE.json
        return 0 if bench_longctx(on_tpu, dev) else 1

    if "--model" in sys.argv:
        i = sys.argv.index("--model")
        if i + 1 >= len(sys.argv):
            print("usage: bench.py [--model gpt_base|resnet50|bert|"
                  "lora_decode] (BENCH_ALL=1 runs every config and writes "
                  "BENCH_ALL.json)", file=sys.stderr)
            sys.exit(2)
        os.environ["BENCH_MODEL"] = sys.argv[i + 1]

    if os.environ.get("BENCH_ALL") == "1":
        # all measured configs -> BENCH_ALL.json artifact (VERDICT r2 weak
        # #2: every README perf claim must trace to a driver-captured or
        # in-repo artifact); flagship line alone on stdout
        os.environ.pop("BENCH_MODEL", None)   # each config picks defaults
        # shell-exported quant knobs must not leak into the bf16 rows —
        # the quantized-variant loop below re-sets them per row
        os.environ.pop("BENCH_WEIGHT_DTYPE", None)
        os.environ.pop("BENCH_KV_DTYPE", None)
        payloads = [_emit(bench_gpt(on_tpu, dev))]
        gate_failed = False
        for fn in (bench_resnet50, bench_bert_finetune, bench_ppyoloe,
                   bench_lora_decode):
            os.environ.pop("BENCH_MODEL", None)
            p = fn(on_tpu, dev)
            if p is None:
                # a ratchet gate breached (conv vs_baseline rows): keep
                # measuring the rest, fail the run at the end — a perf
                # regression fails like a correctness bug
                gate_failed = True
            else:
                payloads.append(p)
        for wdtype, kv in (("int8", ""), ("int4", ""), ("int8", "int8")):
            # weight-only decode variants + the fully-quantized row; both
            # env knobs are forced per row so shell-exported values cannot
            # leak into the matrix
            os.environ["BENCH_WEIGHT_DTYPE"] = wdtype
            os.environ["BENCH_KV_DTYPE"] = kv
            try:
                payloads.append(bench_lora_decode(on_tpu, dev))
            finally:
                os.environ.pop("BENCH_WEIGHT_DTYPE", None)
                os.environ.pop("BENCH_KV_DTYPE", None)
        if on_tpu:
            # weight-dominated decode row (VERDICT r4 item 6): llama2-7B
            # int8 at bs=1 — here the frac metric measures the kernels
            # rather than the KV/LoRA/latency floor (docs/decode_perf.md)
            os.environ.update(BENCH_MODEL="llama2_7b",
                              BENCH_WEIGHT_DTYPE="int8", BENCH_BATCH="1",
                              BENCH_NEW_TOKENS="128")
            try:
                payloads.append(bench_lora_decode(on_tpu, dev))
            finally:
                for k in ("BENCH_MODEL", "BENCH_WEIGHT_DTYPE",
                          "BENCH_BATCH", "BENCH_NEW_TOKENS"):
                    os.environ.pop(k, None)
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_ALL.json"), "w") as f:
            json.dump(payloads, f, indent=1)
        print(json.dumps(payloads[0]))
        return 1 if gate_failed else 0

    mode = os.environ.get("BENCH_MODEL", "")
    if mode.startswith("resnet"):
        return 0 if bench_resnet50(on_tpu, dev) else 1
    if mode.startswith("bert"):
        return 0 if bench_bert_finetune(on_tpu, dev) else 1
    if "yolo" in mode:
        return 0 if bench_ppyoloe(on_tpu, dev) else 1
    if "lora" in mode or mode == "decode":
        return 0 if bench_lora_decode(on_tpu, dev) else 1
    print(json.dumps(bench_gpt(on_tpu, dev)))
    return 0


if __name__ == "__main__":
    # Outer guard: even setup (device enumeration, parallelize) can hit a
    # transient relay fault before the measured loop's own retry kicks in.
    for _attempt in range(3):
        try:
            sys.exit(main())
        except Exception as _e:  # noqa: BLE001
            if not _is_transient(_e) or _attempt == 2:
                raise
            print(f"bench: transient setup error, retrying: {_e}",
                  file=sys.stderr)
            time.sleep(5.0 * (_attempt + 1))
