"""Benchmark gate: flagship GPT (ERNIE-3.0-base-class) pretrain step
throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

The reference publishes no in-tree numbers (BASELINE.md) — `vs_baseline` is
measured against an MFU-derived NCCL/GPU-class target: the north-star asks
for >=40% MFU; we report our measured MFU fraction relative to that target
(vs_baseline = our_MFU / 0.40), so >1.0 beats the reference target.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


# Relay-specific transport-fault signatures only; a bare "INTERNAL" would
# also match deterministic XLA compiler errors and turn a fast failure into
# minutes of futile recompiles.
_TRANSIENT_MARKERS = ("response body closed", "read body", "remote_compile",
                      "Connection reset", "Connection refused", "UNAVAILABLE",
                      "DEADLINE_EXCEEDED", "Socket closed")


class _RetriesExhausted(RuntimeError):
    """Inner retry gave up — final, never re-retried by the outer guard."""


def _is_transient(err: Exception) -> bool:
    if isinstance(err, _RetriesExhausted):
        return False
    msg = str(err)
    return any(m in msg for m in _TRANSIENT_MARKERS)


def _measure_with_retry(make_engine, ids, steps, attempts=6):
    """Warmup + timed loop, retried on transient PJRT-relay transport faults.

    The engine donates its param/opt buffers into the step, so state is
    poisoned once a dispatched step fails — each retry rebuilds the engine
    via make_engine() (the program itself stays compile-cached, so rebuild
    cost is parameter init, not recompilation). Host readback is the only
    reliable fence through the relay (block_until_ready can return at
    enqueue time), so we fence via float() on the final loss.
    """
    last = None
    for attempt in range(attempts):
        try:
            eng = make_engine()
            float(eng.train_batch(ids))  # warmup / compile
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = eng.train_batch(ids)
            final_loss = float(loss)  # device->host readback fences the chain
            dt = time.perf_counter() - t0
            return final_loss, dt
        except Exception as e:  # noqa: BLE001 — classify then re-raise
            if not _is_transient(e):
                raise
            last = e
            eng = None  # release the poisoned engine before rebuilding
            if attempt + 1 < attempts:
                wait = min(2.0 * (attempt + 1), 10.0)
                print(f"bench: transient relay error (attempt {attempt + 1}/"
                      f"{attempts}), retrying in {wait:.0f}s: {e}",
                      file=sys.stderr)
                time.sleep(wait)
    raise _RetriesExhausted(
        f"bench: relay still failing after {attempts} attempts") from last


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import gpt
    from paddle_tpu.models.gpt import GPTConfig, CONFIGS, flops_per_token

    # one-chip bench (the driver runs on a single real TPU chip)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" or "TPU" in str(dev.device_kind)

    name = os.environ.get("BENCH_MODEL", "gpt_base")
    seq_len = int(os.environ.get("BENCH_SEQLEN", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "16" if on_tpu else "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    if not on_tpu:  # CPU smoke: shrink
        name = os.environ.get("BENCH_MODEL", "gpt_tiny")
        seq_len = min(seq_len, 128)

    cfg = GPTConfig(**{**CONFIGS[name],
                       "max_position_embeddings": max(
                           seq_len,
                           CONFIGS[name].get("max_position_embeddings",
                                             seq_len))})

    def make_engine():
        paddle.seed(0)
        model = gpt(name,
                    max_position_embeddings=cfg.max_position_embeddings)
        opt = paddle.optimizer.AdamW(
            learning_rate=1e-4, parameters=model.parameters(),
            grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
        mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
        return dist.parallelize(model, opt, mesh=mesh,
                                compute_dtype="bfloat16" if on_tpu else None)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype("int32"))

    # The axon PJRT relay sporadically drops a response mid-read
    # ("INTERNAL ... response body closed"); these are transient transport
    # faults, not program errors — retry with backoff, rebuilding the engine
    # each attempt (donated buffers are poisoned by a failed step).
    final_loss, dt = _measure_with_retry(make_engine, ids, steps)

    tokens = batch * seq_len * steps
    tps = tokens / dt

    flops_tok = flops_per_token(cfg, seq_len)
    # v5e peak bf16: 197 TFLOP/s; CPU has no meaningful peak — report 0 MFU
    peak = 197e12 if on_tpu else float("inf")
    mfu = tps * flops_tok / peak
    vs_baseline = mfu / 0.40 if on_tpu else 0.0

    print(json.dumps({
        "metric": f"{name} pretrain tokens/sec/chip (seq={seq_len}, bs={batch}, bf16)",
        "value": round(tps, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {"mfu": round(mfu, 4), "loss": round(final_loss, 4),
                  "steps": steps, "platform": dev.platform},
    }))


if __name__ == "__main__":
    # Outer guard: even setup (device enumeration, parallelize) can hit a
    # transient relay fault before the measured loop's own retry kicks in.
    for _attempt in range(3):
        try:
            sys.exit(main())
        except Exception as _e:  # noqa: BLE001
            if not _is_transient(_e) or _attempt == 2:
                raise
            print(f"bench: transient setup error, retrying: {_e}",
                  file=sys.stderr)
            time.sleep(5.0 * (_attempt + 1))
