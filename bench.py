"""Benchmark gate: flagship GPT (ERNIE-3.0-base-class) pretrain step
throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

The reference publishes no in-tree numbers (BASELINE.md) — `vs_baseline` is
measured against an MFU-derived NCCL/GPU-class target: the north-star asks
for >=40% MFU; we report our measured MFU fraction relative to that target
(vs_baseline = our_MFU / 0.40), so >1.0 beats the reference target.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.models import gpt
    from paddle_tpu.models.gpt import GPTConfig, CONFIGS, flops_per_token

    # one-chip bench (the driver runs on a single real TPU chip)
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu" or "TPU" in str(dev.device_kind)

    name = os.environ.get("BENCH_MODEL", "gpt_base")
    seq_len = int(os.environ.get("BENCH_SEQLEN", "1024"))
    batch = int(os.environ.get("BENCH_BATCH", "16" if on_tpu else "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    if not on_tpu:  # CPU smoke: shrink
        name = os.environ.get("BENCH_MODEL", "gpt_tiny")
        seq_len = min(seq_len, 128)

    paddle.seed(0)
    model = gpt(name, max_position_embeddings=max(
        seq_len, CONFIGS[name].get("max_position_embeddings", seq_len)))
    cfg = model.cfg
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))
    mesh = dist.build_mesh(dp=-1, devices=jax.devices()[:1])
    eng = dist.parallelize(model, opt, mesh=mesh,
                           compute_dtype="bfloat16" if on_tpu else None)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype("int32"))

    # warmup (compile); host readback is the only reliable fence through
    # the PJRT relay (block_until_ready can return at enqueue time)
    float(eng.train_batch(ids))

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = eng.train_batch(ids)
    final_loss = float(loss)  # device->host readback fences the whole chain
    dt = time.perf_counter() - t0

    tokens = batch * seq_len * steps
    tps = tokens / dt

    flops_tok = flops_per_token(cfg, seq_len)
    # v5e peak bf16: 197 TFLOP/s; CPU has no meaningful peak — report 0 MFU
    peak = 197e12 if on_tpu else float("inf")
    mfu = tps * flops_tok / peak
    vs_baseline = mfu / 0.40 if on_tpu else 0.0

    print(json.dumps({
        "metric": f"{name} pretrain tokens/sec/chip (seq={seq_len}, bs={batch}, bf16)",
        "value": round(tps, 2),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
        "extra": {"mfu": round(mfu, 4), "loss": round(final_loss, 4),
                  "steps": steps, "platform": dev.platform},
    }))


if __name__ == "__main__":
    sys.exit(main())
