"""Deploy + serve: export a trained model and serve it from a predictor
pool across worker threads.

Reference workflow: train → `paddle.jit.save` → paddle_inference
`Config`/`create_predictor` per thread via `AnalysisPredictor::Clone` /
`services::PredictorPool` (fluid/inference/api/paddle_inference_api.h).
TPU-native: the artifact is an executable StableHLO module (AOT-compiled
once); clones share the immutable executable — XLA replaces the
reference's per-clone analysis-pass pipeline — and each pool member owns
its IO handles so worker threads never race.
"""
import concurrent.futures
import os
import tempfile
import time

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import (Config, DeadlineExceeded, Overloaded,
                                  PredictorPool, ServingPool)

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def train_model(rng):
    X = rng.randn(256, 16).astype("float32")
    W = rng.randn(16, 4).astype("float32")
    y = np.argmax(X @ W, axis=1).astype("int64")
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()
    for _ in range(15 if SMOKE else 80):
        loss = loss_fn(model(paddle.to_tensor(X)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    model.eval()
    return model, X, y


def main():
    paddle.seed(0)
    rng = np.random.RandomState(0)
    model, X, y = train_model(rng)

    with tempfile.TemporaryDirectory(prefix="serve_") as tmp:
        # keep the persistent bucket-executable cache inside the demo dir
        os.environ.setdefault("PADDLE_TPU_COMPILE_CACHE",
                              os.path.join(tmp, "compile-cache"))
        path = os.path.join(tmp, "infer")
        _serve(model, X, y, path)
        _serve_resilient(X, y, path)
        _serve_batched(model, X, os.path.join(tmp, "infer1"))


def _serve(model, X, y, path):
    # export the deploy artifact (fixed serving batch of 8)
    spec = paddle.to_tensor(np.zeros((8, 16), np.float32))
    paddle.jit.save(model, path, input_spec=[spec])

    # serve: 4-member pool; each request leases a member exclusively
    # (pool.acquire()) — with a dynamically-scheduled thread pool, fixed
    # index retrieval could put two in-flight requests on one member
    pool = PredictorPool(Config(path), size=4)
    requests = [X[i:i + 8] for i in range(0, 128, 8)]

    def serve(i):
        with pool.acquire() as p:
            h = p.get_input_handle(p.get_input_names()[0])
            h.copy_from_cpu(requests[i])
            (logits,) = p.run()
        return i, logits.argmax(-1)

    preds = np.empty(128, np.int64)
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as ex:
        for i, cls in ex.map(serve, range(len(requests))):
            preds[i * 8:(i + 1) * 8] = cls

    acc = float((preds == y[:128]).mean())
    print(f"served {len(requests)} requests across 4 threads; "
          f"accuracy {acc:.3f}")
    assert acc > 0.8, acc


def _serve_resilient(X, y, path):
    """Production traffic wants more than exclusive leases: deadlines that
    cover queue wait + execution, and load shedding instead of unbounded
    queueing. ServingPool (docs/serving.md) adds both, plus member
    supervision (re-clone on failure, circuit breaker, hang detection)."""
    # generous default deadline: the first request pays the one-off XLA
    # compile of the loaded module, which a loaded CI box can stretch
    pool = ServingPool(Config(path), size=2, max_queue_depth=2,
                       default_timeout=30.0)

    # normal traffic: infer() leases a healthy member and enforces the
    # deadline end-to-end, raising typed errors instead of hanging
    (logits,) = pool.infer([X[:8]])
    acc = float((logits.argmax(-1) == y[:8]).mean())
    print(f"resilient pool served a batch; accuracy {acc:.3f}")

    # deadline: a request admitted with no time budget left is refused
    # BEFORE any compute is wasted
    try:
        pool.infer([X[:8]], timeout=-1.0)
        raise AssertionError("expected DeadlineExceeded")
    except DeadlineExceeded:
        print("past-deadline request rejected before compute (typed)")

    # overload shedding: saturate both members with slow requests and
    # fill the 2-deep admission queue — further traffic is shed with
    # `Overloaded` instead of queueing unboundedly
    def slow(pred):
        time.sleep(0.3)
        return pred.run([X[:8]])

    in_flight = [pool.submit(slow) for _ in range(2)]   # occupy members
    time.sleep(0.05)
    backlog = [pool.submit(slow) for _ in range(2)]     # fill the queue
    shed = 0
    for _ in range(4):
        try:
            pool.submit(slow)
        except Overloaded:
            shed += 1
    for f in in_flight + backlog:
        f.result()
    stats = pool.stats()
    print(f"overload: {stats['admitted']} admitted, {stats['shed']} shed, "
          f"{stats['completed']} completed")
    assert shed == 4 and stats["shed"] >= 4

    # graceful drain: stop admissions, finish in-flight work, release
    drained = pool.shutdown(drain_timeout=5.0)
    print(f"drained cleanly: {drained}")
    assert drained


def _serve_batched(model, X, path):
    # -- dynamic request batching (docs/serving.md) ----------------------
    # single-example artifact: each request is one example; the pool
    # coalesces concurrent requests into bucketed batches and serves each
    # with ONE AOT dispatch, outputs bit-identical to unbatched execution
    from paddle_tpu.inference import BatchConfig

    paddle.jit.save(model, path, input_spec=[
        paddle.to_tensor(np.zeros((1, 16), np.float32))])
    pool = ServingPool(Config(path), size=2, default_timeout=10.0,
                       batching=BatchConfig(buckets=(1, 2, 4, 8),
                                            max_wait_ms=3.0))
    pool.warmup()   # compile (or disk-load) every bucket before traffic
    n = 16 if SMOKE else 64
    want = [model(paddle.to_tensor(X[i:i + 1])).numpy() for i in range(n)]
    with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
        outs = list(ex.map(
            lambda i: pool.infer([X[i:i + 1]])[0], range(n)))
    assert all((outs[i] == want[i]).all() for i in range(n))
    b = pool.stats()["batch"]
    print(f"batched: {b['requests']} requests in {b['formed']} dispatches "
          f"(occupancy {b['occupancy']:.2f}, by bucket "
          f"{b['executed_by_bucket']}, compile {b['compile']})")
    assert b["formed"] < n   # batching actually coalesced
    pool.shutdown(drain_timeout=5.0)


if __name__ == "__main__":
    main()
