"""to_static with graph breaks (reference journey: @to_static just works —
the SOT fallback runs unsupported constructs eagerly instead of erroring,
jit/sot/translate.py contract).

Shows: a convertible branch compiling to lax.cond, a generator-driven loop
breaking the graph (warn once, run eagerly, still train), and
full_graph=True turning the same break into a loud error.
"""
import os
import warnings

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def main():
    paddle.seed(0)
    lin = nn.Linear(4, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())

    def chunks(x):          # generator with a data-dependent stop:
        i = 0               # unconvertible -> graph break -> eager
        while float((x[i:] ** 2).sum()) > 1e-6 and i < 4:
            yield x[i:i + 2]
            i += 2

    @paddle.jit.to_static
    def step(x, y):
        acc = paddle.zeros([1])
        for c in chunks(x.reshape([-1])):
            acc = acc + c.sum()
        pred = lin(x)
        return ((pred - y) ** 2).mean() + 0.0 * acc

    rng = np.random.RandomState(0)
    X = rng.randn(8, 4).astype("float32")
    Y = (X @ np.array([[1.0], [2.0], [-1.0], [0.5]], "float32"))
    steps = 10 if SMOKE else 40
    losses = []
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for _ in range(steps):
            loss = step(paddle.to_tensor(X), paddle.to_tensor(Y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    breaks = [w for w in rec if "graph break" in str(w.message)]
    print(f"graph break warned once: {len(breaks) == 1}; "
          f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0]

    # the same construct under full_graph=True is a loud error
    @paddle.jit.to_static(full_graph=True)
    def strict(x):
        if float(x.sum()) > 0:
            return x + 1.0
        return x

    try:
        strict(paddle.to_tensor(np.float32([1.0])))
        raise SystemExit("expected full_graph=True to raise")
    except Exception as e:
        print("full_graph=True raises:", type(e).__name__)
    print("OK")


if __name__ == "__main__":
    main()
