"""Static-graph workflow: build once, Executor.run per step.

Reference: the classic fit-a-line static program
(enable_static -> static.data -> net -> minimize -> exe.run(feed,
fetch_list)). Here the Program captures ops at build time and the
Executor replays them — one exe.run == one training step.
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import static

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def main():
    paddle.enable_static()
    try:
        rng = np.random.RandomState(0)
        true_w = rng.randn(13, 1).astype("float32")
        xs = rng.randn(128, 13).astype("float32")
        ys = xs @ true_w

        main_prog = static.Program()
        with static.program_guard(main_prog):
            x = static.data("x", [None, 13], "float32")
            y = static.data("y", [None, 1], "float32")
            lin = paddle.nn.Linear(13, 1)
            pred = lin(x)
            loss = paddle.nn.functional.mse_loss(pred, y)
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=lin.parameters())
            opt.minimize(loss)

        exe = static.Executor()
        exe.run(static.default_startup_program())
        steps = 10 if SMOKE else 60
        for step in range(steps):
            lv, = exe.run(main_prog, feed={"x": xs, "y": ys},
                          fetch_list=[loss])
            if step % 20 == 0:
                print(f"step {step}: loss {float(lv):.5f}")

        # inference clone: same graph, training hook dropped
        test_prog = main_prog.clone(for_test=True)
        out, = exe.run(test_prog, feed={"x": xs[:4], "y": ys[:4]},
                       fetch_list=[pred])
        print("predictions:", out[:2].ravel())
    finally:
        paddle.disable_static()


if __name__ == "__main__":
    main()
