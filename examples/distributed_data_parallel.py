"""Multi-process data-parallel training via dist.spawn.

Reference workflow: paddle.distributed.spawn launching N trainers that
init_parallel_env and train with DDP semantics. Here each process is a
controller; the parent hosts the native coordination store, and the
cross-process gradient all-reduce comes from GSPMD once jax.distributed
joins the processes into one mesh (see tests/test_multiprocess_dist.py
for that full path). This example shows the spawn + store control plane
with an explicit p2p/object exchange.
"""
import numpy as np


def worker(tag):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import rpc

    dist.init_parallel_env()
    rank, world = dist.get_rank(), dist.get_world_size()

    # object collective over the store
    infos = []
    dist.all_gather_object(infos, {"rank": rank, "tag": tag})
    if rank == 0:
        print("gathered:", sorted(i["rank"] for i in infos))

    # p2p tensor exchange
    if rank == 0:
        dist.send(paddle.to_tensor(np.float32([3.14])), dst=1)
    else:
        buf = paddle.zeros([1])
        dist.recv(buf, src=0)
        print(f"rank {rank} received {float(buf.numpy()[0]):.2f}")

    # control-plane rpc between workers
    rpc.init_rpc(f"trainer{rank}")
    try:
        peer = f"trainer{1 - rank}"
        out = rpc.rpc_sync(peer, sum, args=([rank, 10],), timeout=60)
        print(f"rank {rank}: rpc_sync({peer}) -> {out}")
    finally:
        rpc.shutdown()


def main():
    import paddle_tpu.distributed as dist
    dist.spawn(worker, args=("demo",), nprocs=2)
    print("spawn finished")


if __name__ == "__main__":
    main()
