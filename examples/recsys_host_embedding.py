"""Recommender embeddings beyond HBM: the parameter-server answer.

Reference workflow: PS sparse tables (pull_sparse/push_sparse against
host/SSD-tier tables). TPU-native: `HostOffloadedEmbedding` keeps the
table in HOST memory (jax `pinned_host` memory kind), pulls only the
deduplicated rows a batch touches, and applies sparse adagrad pushes
on the table itself — device memory never sees the full table or a
dense gradient.
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import HostOffloadedEmbedding

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def main():
    n_rows = 50_000 if SMOKE else 2_000_000   # scale to host DRAM
    dim = 16
    table = HostOffloadedEmbedding(n_rows, dim, optimizer="adagrad",
                                   learning_rate=0.05, cache_size=1024)
    print(f"table: {n_rows} x {dim} in {table.memory_kind} memory")

    tower = nn.Sequential(nn.Linear(2 * dim, 32), nn.ReLU(),
                          nn.Linear(32, 1))
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=tower.parameters())

    rng = np.random.RandomState(0)
    steps = 5 if SMOKE else 50
    table.train()
    for step in range(steps):
        user = rng.randint(0, n_rows, (64,)).astype("int32")
        item = rng.randint(0, n_rows, (64,)).astype("int32")
        label = (user % 2 == item % 2).astype("float32")
        ue = table(paddle.to_tensor(user))
        ie = table(paddle.to_tensor(item))
        feats = paddle.concat([ue, ie], axis=-1)
        logits = tower(feats).squeeze(-1)
        loss = nn.functional.binary_cross_entropy_with_logits(
            logits, paddle.to_tensor(label))
        loss.backward()       # dense grads -> tower; sparse push -> table
        opt.step()
        opt.clear_grad()
        if step % 10 == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    # serving: eval mode uses the HBM hot-row LRU cache
    table.eval()
    scores = tower(paddle.concat(
        [table(paddle.to_tensor(np.arange(8, dtype=np.int32))),
         table(paddle.to_tensor(np.arange(8, dtype=np.int32)))], axis=-1))
    print("serving scores:", scores.numpy().ravel()[:4])


if __name__ == "__main__":
    main()
