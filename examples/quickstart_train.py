"""Quickstart: eager training loop + checkpoint round-trip.

The canonical first-contact workflow (reference: the quickstart in the
PaddlePaddle docs — dygraph model, optimizer, cross-entropy, save/load).
Runs on CPU or TPU unchanged.
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def main():
    rng = np.random.RandomState(0)
    X = rng.randn(256, 16).astype("float32")
    W = rng.randn(16, 4).astype("float32")
    y = np.argmax(X @ W, axis=1).astype("int64")

    model = nn.Sequential(
        nn.Linear(16, 64), nn.ReLU(), nn.Dropout(0.1), nn.Linear(64, 4))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    steps = 10 if SMOKE else 60
    for step in range(steps):
        xb, yb = paddle.to_tensor(X), paddle.to_tensor(y)
        loss = loss_fn(model(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 20 == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    model.eval()
    acc = float((np.argmax(model(paddle.to_tensor(X)).numpy(), 1)
                 == y).mean())
    print(f"train accuracy: {acc:.3f}")

    # checkpoint round-trip
    paddle.save(model.state_dict(), "/tmp/quickstart.pdparams")
    clone = nn.Sequential(
        nn.Linear(16, 64), nn.ReLU(), nn.Dropout(0.1), nn.Linear(64, 4))
    clone.set_state_dict(paddle.load("/tmp/quickstart.pdparams"))
    clone.eval()
    acc2 = float((np.argmax(clone(paddle.to_tensor(X)).numpy(), 1)
                  == y).mean())
    assert acc2 == acc
    print("checkpoint round-trip ok")


if __name__ == "__main__":
    main()
