"""GraphSAGE with neighbor sampling over the in-memory CSR graph store.

Reference workflow: PGL-style GraphSAGE fed by the PS graph table's
neighbor sampling (paddle/fluid/distributed/ps/table/common_graph_table.h,
python/paddle/geometric/sampling/neighbors.py) — minibatch of target
nodes → multi-hop uniform neighbor sampling → reindex to compact local
ids → stacked mean-aggregator convolutions → node classification.

TPU design: topology + sampling stay on host (data-dependent shapes);
each sampled minibatch crosses to the device as dense features + edge
index arrays, and the convolution stack is ordinary jit-able segment ops
(send_u_recv).
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import geometric, nn

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"

N_COMMUNITIES = 4
NODES_PER_COMM = 64
FEAT_DIM = 16
HIDDEN = 32


def make_community_graph(rng):
    """Synthetic stochastic block model: dense intra-community edges,
    sparse bridges; node features = noisy community signature."""
    n = N_COMMUNITIES * NODES_PER_COMM
    comm = np.repeat(np.arange(N_COMMUNITIES), NODES_PER_COMM)
    src, dst = [], []
    for u in range(n):
        same = np.nonzero(comm == comm[u])[0]
        nbrs = rng.choice(same[same != u], size=8, replace=False)
        other = np.nonzero(comm != comm[u])[0]
        bridge = rng.choice(other, size=1)
        for v in list(nbrs) + list(bridge):
            src.append(v)
            dst.append(u)
    sig = rng.randn(N_COMMUNITIES, FEAT_DIM).astype("float32")
    feats = sig[comm] + 0.8 * rng.randn(n, FEAT_DIM).astype("float32")
    graph = geometric.Graph(np.stack([src, dst]), num_nodes=n)
    return graph, feats, comm.astype("int64")


class SageConv(nn.Layer):
    """Mean-aggregator GraphSAGE layer: W_s·h_v + W_n·mean(h_u, u→v)."""

    def __init__(self, in_dim, out_dim):
        super().__init__()
        self.lin_self = nn.Linear(in_dim, out_dim)
        self.lin_neigh = nn.Linear(in_dim, out_dim)

    def forward(self, h, src, dst, num_targets):
        agg = geometric.send_u_recv(h, src, dst, reduce_op="mean",
                                    out_size=num_targets)
        return self.lin_self(h[:num_targets]) + self.lin_neigh(agg)


class GraphSAGE(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv1 = SageConv(FEAT_DIM, HIDDEN)
        self.conv2 = SageConv(HIDDEN, N_COMMUNITIES)
        self.act = nn.ReLU()

    def forward(self, feats, hops):
        """hops: [(src, dst, num_targets)] outermost-first from
        Graph.sample_subgraph; consume innermost-first."""
        h = feats
        convs = [self.conv1, self.conv2]
        for conv, (src, dst, nf) in zip(convs, reversed(hops)):
            h = conv(h, src, dst, nf)
            if conv is not self.conv2:
                h = self.act(h)
        return h


def main():
    rng = np.random.RandomState(0)
    paddle.seed(0)
    graph, feats, labels = make_community_graph(rng)
    model = GraphSAGE()
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    loss_fn = nn.CrossEntropyLoss()

    n = graph.num_nodes
    steps = 12 if SMOKE else 120
    batch = 32
    first = last = None
    for step in range(steps):
        targets = rng.choice(n, size=batch, replace=False)
        # 2-hop frontier expansion: 5 then 5 sampled inbound neighbors
        node_ids, hops = graph.sample_subgraph(targets, [5, 5])
        h = paddle.to_tensor(feats[np.asarray(node_ids.numpy())])
        logits = model(h, hops)
        loss = loss_fn(logits, paddle.to_tensor(labels[targets]))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step == 0:
            first = float(loss.numpy())
        last = float(loss.numpy())

    # full-graph eval through the same sampled pipeline
    node_ids, hops = graph.sample_subgraph(np.arange(n), [10, 10])
    h = paddle.to_tensor(feats[np.asarray(node_ids.numpy())])
    pred = np.asarray(model(h, hops).numpy()).argmax(-1)
    acc = float((pred == labels).mean())
    print(f"loss {first:.3f} -> {last:.3f}; full-graph accuracy {acc:.3f}")
    assert last < first, "training did not reduce the loss"
    if not SMOKE:
        assert acc > 0.9, acc


if __name__ == "__main__":
    main()
