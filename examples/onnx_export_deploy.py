"""ONNX deployment export (reference journey: train in Paddle →
paddle.onnx.export → serve from an ONNX runtime).

Here the .onnx protobuf is emitted by the in-repo writer and verified by
re-parsing + numerically executing it with the numpy reference runner —
no external onnx packages (zero egress).
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.onnx as onnx
from paddle_tpu import nn
from paddle_tpu.static import InputSpec

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


class SmallCNN(nn.Layer):
    def __init__(self):
        super().__init__()
        self.conv = nn.Conv2D(1, 8, 3, padding=1)
        self.fc = nn.Linear(8 * 7 * 7, 10)

    def forward(self, x):
        h = paddle.nn.functional.relu(self.conv(x))
        h = paddle.nn.functional.max_pool2d(h, 2)
        h = paddle.reshape(h, [h.shape[0], -1])
        return paddle.nn.functional.softmax(self.fc(h), axis=-1)


def main():
    paddle.seed(0)
    model = SmallCNN()

    # (a short fine-tune would go here; export works on any trained state)
    model.eval()
    x = np.random.RandomState(0).randn(4, 1, 14, 14).astype("float32")
    live = model(paddle.to_tensor(x)).numpy()

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.onnx")
        onnx.export(model, path, input_spec=[InputSpec([4, 1, 14, 14],
                                                       "float32")])
        print(f"wrote {os.path.getsize(path)} bytes of ONNX (opset "
              f"{onnx.OPSET})")

        parsed = onnx.load(path)
        print("nodes:", [n.op_type for n in parsed.nodes])
        served = onnx.reference_run(parsed, {parsed.inputs[0][0]: x})[0]

    err = np.abs(served - live).max()
    print(f"deployed-vs-live max abs diff: {err:.2e}")
    assert err < 1e-3
    print("OK")


if __name__ == "__main__":
    main()
