"""LoRA fine-tuning + autoregressive generation (BASELINE config 5 shape).

Reference workflow: PaddleNLP LoRA fine-tune then generate. Adapters
train (base frozen), generation runs KV-cached as one compiled scan,
merge_lora() folds adapters for deployment.
"""
import os

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import gpt, generate, GenerationConfig
from paddle_tpu.nn.lora import (
    LoRAConfig, apply_lora, lora_parameters, mark_only_lora_as_trainable,
    merge_lora,
)

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def main():
    paddle.seed(0)
    model = gpt("gpt_tiny")
    apply_lora(model, LoRAConfig(r=8, lora_alpha=16))
    mark_only_lora_as_trainable(model)
    n_train = sum(int(np.prod(p.shape)) for p in lora_parameters(model))
    n_total = sum(int(np.prod(p.shape))
                  for _, p in model.named_parameters())
    print(f"trainable adapter params: {n_train} / {n_total} "
          f"({100.0 * n_train / n_total:.2f}%)")

    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=lora_parameters(model))
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 256, (4, 32)).astype("int32"))
    steps = 5 if SMOKE else 30
    for step in range(steps):
        loss = model.loss(ids)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 10 == 0:
            print(f"step {step}: loss {float(loss):.4f}")

    # deployment: fold adapters, generate with the KV cache
    merge_lora(model)
    model.eval()
    prompt = paddle.to_tensor(rng.randint(0, 256, (2, 8)).astype("int32"))
    out = generate(model, prompt, GenerationConfig(
        max_new_tokens=8 if SMOKE else 32, do_sample=True, top_k=20,
        temperature=0.9, use_cache=True))
    print("generated shape:", out.shape)
    print("first sequence:", out.numpy()[0].tolist())


if __name__ == "__main__":
    main()
