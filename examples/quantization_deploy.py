"""QAT -> convert -> export: the quantized deployment pipeline.

Reference workflow: paddle.quantization QAT training, the convert pass
to an inference program, then jit.save for the Predictor. The converted
model holds int8 weights + frozen scales as buffers (1/4 the weight
memory) and serializes through state_dict/jit.save unchanged.
"""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import QAT, QuantConfig

SMOKE = os.environ.get("EXAMPLES_SMOKE") == "1"


def main():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 4))
    qat = QAT(QuantConfig(weight_bits=8, activation_bits=8))
    qat.quantize(model)

    rng = np.random.RandomState(0)
    X = rng.randn(256, 16).astype("float32")
    y = rng.randint(0, 4, 256).astype("int64")
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=model.parameters())
    lf = nn.CrossEntropyLoss()
    steps = 5 if SMOKE else 40
    for step in range(steps):
        loss = lf(model(paddle.to_tensor(X)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    print(f"QAT final loss: {float(loss):.4f}")

    fp_out = model(paddle.to_tensor(X[:8])).numpy()
    qat.convert(model)          # frozen-scale int8 inference layers
    model.eval()
    q_out = model(paddle.to_tensor(X[:8])).numpy()
    err = np.abs(q_out - fp_out).max() / (np.abs(fp_out).max() + 1e-9)
    print(f"int8 vs fake-quant relative error: {err:.4f}")
    sub = dict(model.named_sublayers())["0"]
    print("deployed weight dtype:", sub.weight_int8.dtype)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "quant_infer")
        paddle.jit.save(model, path,
                        input_spec=[paddle.to_tensor(X[:8])])
        loaded = paddle.jit.load(path)
        np.testing.assert_allclose(
            loaded(paddle.to_tensor(X[:8])).numpy(), q_out, rtol=1e-5)
        print("jit.save/load round-trip ok")


if __name__ == "__main__":
    main()
