"""Hybrid-parallel GPT training with the auto-parallel planner.

Reference workflow: fleet hybrid-parallel training (dp/mp/pp/sharding
degrees in DistributedStrategy). TPU-native: the planner picks the
degrees from a cost model, `parallelize` compiles ONE sharded train
step over the mesh, GSPMD inserts every collective.

Run on CPU with a virtual mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/hybrid_parallel_gpt.py
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402


def main():
    import jax
    if jax.default_backend() != "tpu":
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.auto_parallel import auto_parallelize, plan
    from paddle_tpu.models import gpt

    paddle.seed(0)
    model = gpt("gpt_tiny")
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters(),
                                 grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0))

    step = auto_parallelize(model, opt, batch_size=8, seq_len=64)
    print("planner decision:")
    print(step.plan.rationale())

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, 256, (8, 64)).astype("int32"))
    for i in range(5):
        loss = step.train_batch(ids)
        print(f"step {i}: loss {float(loss):.4f}")

    # manual degrees work too (fleet-style); tensor-parallel needs >1 chip
    mp = 2 if jax.device_count() % 2 == 0 and jax.device_count() >= 2 else 1
    mesh = dist.build_mesh(dp=-1, mp=mp)
    step2 = dist.parallelize(model, opt, mesh=mesh, sharding_stage=2)
    print("manual mesh:", dict(mesh.shape))
    print(f"manual-mesh loss: {float(step2.train_batch(ids)):.4f}")


if __name__ == "__main__":
    main()
